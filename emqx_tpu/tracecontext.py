"""End-to-end message-lifecycle tracing: sampled trace contexts
through the batched hot path, across cluster links and multicore
workers.

The `emqx_external_trace`/OTLP-spans half of the reference's
observability story (emqx_opentelemetry's emqx_otel_trace behavior),
done the way Dapper-style tracers survive high-volume paths: a seeded
HEAD sampler decides at publish ingress, the decision rides the
message as a tiny ``TraceContext`` (a parallel column through the
batched pipeline — unsampled messages allocate NOTHING), and spans are
emitted once per window from the profiler's existing ``WindowRecord``
stage timestamps, so the dispatch loops take zero additional clock
reads.

Three boundaries the per-process window profiler (PR 4) cannot see
across are covered by context propagation:

  * cluster forwards — ``ClusterNode.forward`` stamps the context into
    the forwarded copy's MQTT 5 user properties (key ``TRACE_PROP``),
    so the peer's forwarded-dispatch span parents to the origin's
    ``message.forward`` span;
  * cluster links — the ``$LINK/msg`` wrapper carries the same field
    end-to-end, closed locally even when the link's failpoint eats the
    egress (chaos attribution);
  * multicore workers — worker processes cluster over loopback using
    the ordinary inter-node transport, so a cross-worker hop is traced
    exactly like a cross-node one, with per-worker process tracks in
    the merged Perfetto timeline.

Spans land in a bounded in-process ``TraceStore`` (queryable over
``GET /api/v5/tracing/...`` by trace id AND by message id, and from
``ctl tracing``) and flow out through the existing OTLP exporter
(otel.py) when one is configured.  ``chrome_trace`` renders any set of
span dicts — one node's store or several nodes' merged — as a
Perfetto-loadable timeline with one PROCESS per node/worker and flow
events linking each forward hop to its remote dispatch span.

Spans hold only ids, names and scalar attributes — never the message
or its payload — so the store cannot keep window buffers alive.
"""

from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import topic as T

# v5-user-property-shaped carrier: ("emqx-tp-trace", "<trace32>-<span16>")
# injected into the FORWARDED copy's properties at each egress seam and
# stripped at the peer's ingress, so subscriber-visible bytes never
# change (the chaos/property suites pin this down)
TRACE_PROP = "emqx-tp-trace"


def encode_ctx(trace_id: str, span_id: str) -> str:
    return f"{trace_id}-{span_id}"


def decode_ctx(value: str) -> Optional[Tuple[str, str]]:
    trace_id, _, span_id = value.partition("-")
    if len(trace_id) == 32 and len(span_id) == 16:
        return trace_id, span_id
    return None


def inject_props(properties: Dict, trace_id: str, span_id: str) -> None:
    """Append the context pair to ``user_property`` (any stale copy of
    the key is dropped first)."""
    ups = [
        (k, v)
        for k, v in (properties.get("user_property", ()) or ())
        if k != TRACE_PROP
    ]
    ups.append((TRACE_PROP, encode_ctx(trace_id, span_id)))
    properties["user_property"] = ups


def extract_strip(properties: Dict) -> Optional[Tuple[str, str]]:
    """Pop the context pair out of ``user_property`` and return
    (trace_id, span_id), or None.  Pairs may be tuples OR 2-lists (the
    binary cluster wire round-trips them through JSON)."""
    ups = properties.get("user_property")
    if not ups:
        return None
    found = None
    kept = []
    for pair in ups:
        k, v = pair
        if k == TRACE_PROP:
            found = decode_ctx(v)
        else:
            kept.append(pair)
    if found is not None:
        if kept:
            properties["user_property"] = kept
        else:
            del properties["user_property"]
    return found


class TraceContext:
    """One sampled message's context: the trace it belongs to, the
    span id its children parent to, and (for a message that crossed a
    boundary) the remote parent span id."""

    __slots__ = ("trace_id", "span_id", "parent_id", "remote")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None,
                 remote: bool = False) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.remote = remote


class TraceStore:
    """Bounded in-process span store, indexed by trace id AND by
    message id.  Eviction is whole-trace FIFO: when the ``max_traces``
    cap is hit the oldest trace goes, taking its message-id index
    entries with it — the store can never grow without bound no matter
    how chaotic the traffic (the link-drop chaos suite asserts this)."""

    def __init__(self, max_traces: int = 512) -> None:
        self.max_traces = max(int(max_traces), 1)
        self._traces: "OrderedDict[str, List[Dict]]" = OrderedDict()
        self._by_mid: Dict[str, str] = {}
        self._lock = threading.Lock()
        self.stats = {"spans": 0, "evicted": 0}

    def add(self, span: Dict) -> None:
        tid = span["trace_id"]
        mid = span.get("mid") or ""
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                spans = self._traces[tid] = []
                while len(self._traces) > self.max_traces:
                    old_tid, old_spans = self._traces.popitem(last=False)
                    self.stats["evicted"] += 1
                    for s in old_spans:
                        m = s.get("mid") or ""
                        if m and self._by_mid.get(m) == old_tid:
                            del self._by_mid[m]
            spans.append(span)
            self.stats["spans"] += 1
            if mid and mid not in self._by_mid:
                self._by_mid[mid] = tid

    def get(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def by_mid(self, mid: str) -> Optional[str]:
        with self._lock:
            return self._by_mid.get(mid)

    def spans(self) -> List[Dict]:
        with self._lock:
            out: List[Dict] = []
            for spans in self._traces.values():
                out.extend(spans)
            return out

    def traces(self, limit: int = 64) -> List[Dict]:
        """Newest-first trace summaries."""
        with self._lock:
            items = list(self._traces.items())
        out = []
        for tid, spans in reversed(items[-max(limit, 0):]):
            first = min(s["start_ns"] for s in spans)
            last = max(s["end_ns"] for s in spans)
            root = next(
                (s for s in spans if not s.get("parent_id")), spans[0]
            )
            out.append({
                "trace_id": tid,
                "start_ns": first,
                "duration_ms": round((last - first) / 1e6, 3),
                "n_spans": len(spans),
                "topic": root.get("attrs", {}).get("topic", ""),
                "nodes": sorted({s.get("node", "") for s in spans}),
            })
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._by_mid.clear()
            self.stats = {"spans": 0, "evicted": 0}


class HeadSampler:
    """Seeded head sampler: a message is sampled when the coin lands
    under ``rate`` OR its topic matches one of the configured topic
    filters (operators pin the flows they are debugging).  ``seed``
    makes chaos runs reproduce their sampling decisions bit-for-bit."""

    def __init__(self, rate: float = 0.0,
                 topic_filters: Sequence[str] = (),
                 seed: Optional[int] = None) -> None:
        self.configure(rate, topic_filters, seed)

    def configure(self, rate: float,
                  topic_filters: Sequence[str] = (),
                  seed: Optional[int] = None) -> None:
        self.rate = min(max(float(rate), 0.0), 1.0)
        self.topic_filters = [str(f) for f in topic_filters]
        self.seed = seed
        self._rng = random.Random(seed)

    @property
    def active(self) -> bool:
        return self.rate > 0.0 or bool(self.topic_filters)

    def decide(self, topic: str) -> bool:
        # rate-sampling skips $-reserved topics ($SYS heartbeats, the
        # $LINK egress wrapper, $delayed) — their traffic is broker
        # plumbing, and the wrapper hop is already covered by the
        # ORIGINAL message's link.forward span.  An explicit topic
        # filter still pins them when an operator asks.
        if topic[:1] != "$":
            if self.rate >= 1.0:
                return True
            if self.rate > 0.0 and self._rng.random() < self.rate:
                return True
        for flt in self.topic_filters:
            if T.match(topic, flt):
                return True
        return False

    def span_id(self) -> str:
        return f"{self._rng.getrandbits(64):016x}"

    def trace_id(self) -> str:
        return f"{self._rng.getrandbits(128):032x}"


class PendingForward:
    """A forward span opened at an egress seam, closed when the flush
    learns the outcome (cast done, sync reply, failpoint drop, dead
    peer).  Holds ONLY the tracer and scalar fields — never the
    message — and emits at most once, so an egress path that reports
    twice (retry after re-queue) cannot double-count."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "LifecycleTracer", span: Dict) -> None:
        self._tracer = tracer
        self.span = span

    @property
    def span_id(self) -> str:
        return self.span["span_id"]

    def end(self, ok: bool, detail: str = "") -> None:
        tracer, self._tracer = self._tracer, None
        if tracer is None:
            return
        span = self.span
        span["end_ns"] = time.time_ns()
        span["attrs"]["ok"] = bool(ok)
        if detail:
            span["attrs"]["detail"] = detail
        tracer.emit(span)


class LifecycleTracer:
    """The broker's per-message lifecycle tracer: head sampling at
    publish ingress, context extraction at every boundary ingress,
    window-level span emission from ``WindowRecord`` timestamps, and
    forward spans at the egress seams.

    Everything per-message is gated on ``active`` (rate 0 with no
    topic filters = every hot-path call site short-circuits on one
    attribute load) and on the message CARRYING a context — an
    unsampled window does no per-message work beyond the attribute
    probe the e2e profiler loop already pays."""

    def __init__(self, cfg=None, node: str = "emqx_tpu",
                 store: Optional[TraceStore] = None) -> None:
        rate = getattr(cfg, "sample_rate", 0.0) if cfg is not None else 0.0
        filters = getattr(cfg, "topic_filters", ()) if cfg is not None \
            else ()
        seed = getattr(cfg, "seed", None) if cfg is not None else None
        enable = bool(getattr(cfg, "enable", False)) if cfg is not None \
            else False
        self.node = node
        self.sampler = HeadSampler(rate, filters, seed)
        self.store = store or TraceStore(
            getattr(cfg, "store_max", 512) if cfg is not None else 512
        )
        self.enable = enable
        # wired by the OtelExporter when trace export is on: called
        # with each finished span dict (OTLP fan-out)
        self.on_export: Optional[Callable[[Dict], None]] = None
        self.stats = {"sampled": 0, "remote": 0, "forwards": 0}
        self._recompute()

    # ------------------------------------------------------- config

    def _recompute(self) -> None:
        # active == enabled, NOT enabled-and-sampling: a node with
        # rate 0 must still ADOPT upstream contexts (the natural
        # deployment samples at the ingress edge and enables
        # everywhere else).  Fresh sampling is separately gated by the
        # sampler's own rate/filters inside ingress().
        self.active = bool(self.enable)

    def configure(self, enable: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  topic_filters: Optional[Sequence[str]] = None,
                  seed: Optional[int] = None) -> None:
        if enable is not None:
            self.enable = bool(enable)
        self.sampler.configure(
            self.sampler.rate if sample_rate is None else sample_rate,
            self.sampler.topic_filters if topic_filters is None
            else topic_filters,
            self.sampler.seed if seed is None else seed,
        )
        self._recompute()

    def info(self) -> Dict:
        return {
            "enable": self.enable,
            "active": self.active,
            "sampling": self.sampler.active,
            "sample_rate": self.sampler.rate,
            "topic_filters": list(self.sampler.topic_filters),
            "seed": self.sampler.seed,
            "node": self.node,
            "traces": len(self.store),
            "store_max": self.store.max_traces,
            **self.stats,
            **self.store.stats,
        }

    # ------------------------------------------------------ ingress

    def ingress(self, msg, sample: bool = True) -> None:
        """Publish-ingress decision for one message: honor an upstream
        context (the message crossed a boundary already sampled), else
        flip the head-sampler coin.  ``sample=False`` (forwarded-frame
        ingress) only adopts upstream contexts — the head decision is
        made ONCE, at the origin node.  Idempotent — the async prepare
        path may funnel through the sync one."""
        if getattr(msg, "_trace_ctx", None) is not None:
            return
        remote = extract_strip(msg.properties) if msg.properties else None
        if remote is None:
            hdr = msg.headers.pop("trace_ctx", None) if msg.headers \
                else None
            if hdr:
                remote = decode_ctx(str(hdr))
        if remote is not None:
            trace_id, parent_id = remote
            msg._trace_ctx = TraceContext(
                trace_id, self.sampler.span_id(), parent_id, remote=True
            )
            self.stats["remote"] += 1
            return
        if not sample or msg.sys:
            return
        if self.sampler.decide(msg.topic):
            msg._trace_ctx = TraceContext(
                self.sampler.trace_id(), self.sampler.span_id()
            )
            self.stats["sampled"] += 1

    # ------------------------------------------------------- windows

    def window_spans(self, msgs: Sequence, counts: Sequence[int],
                     rec=None, n_clients: int = 0,
                     clients: Optional[Dict] = None) -> None:
        """Emit one span per SAMPLED message of a finished dispatch
        window, timed entirely from the window's flight-recorder entry
        (``rec``): span = ingress→flush for a local publish, window
        start→flush for a forwarded hop, with one span event per
        pipeline stage and the engine path / breaker state / failpoint
        fires attached — no clock was read for any of this beyond what
        the profiler already recorded.  Called once per window, OUTSIDE
        the dispatch loops."""
        ctxs = [
            (i, ctx) for i, m in enumerate(msgs)
            for ctx in (getattr(m, "_trace_ctx", None),)
            if ctx is not None
        ]
        if not ctxs:
            return
        if rec is not None and rec.spans:
            w_start = rec.wall0
            last = rec.spans[-1]
            w_end = rec.wall0 + last[1] + last[2]
            stage_events = [
                {
                    "name": "stage." + name,
                    "ts_ns": int((rec.wall0 + off + dur) * 1e9),
                    "attrs": {"dur_us": round(dur * 1e6, 1)},
                }
                for name, off, dur in rec.spans
            ] + [
                {
                    "name": "stage." + name,
                    "ts_ns": int(w_end * 1e9),
                    "attrs": {"dur_us": round(dur * 1e6, 1)},
                }
                for name, dur in rec.subs
            ]
            path = rec.path
            breaker = rec.breaker_open
            source = rec.source
        else:
            # profiler disabled: one clock read per WINDOW, never per
            # message, and only here (off the dispatch loops)
            w_end = time.time()
            w_start = min(
                (msgs[i].timestamp for i, _ in ctxs
                 if msgs[i].timestamp), default=w_end,
            )
            stage_events = []
            path = ""
            breaker = False
            source = "publish"
        fp_events = _failpoint_events(w_start, w_end)
        forwarded = source == "forwarded"
        for i, ctx in ctxs:
            msg = msgs[i]
            start = w_start if forwarded or not msg.timestamp \
                else min(msg.timestamp, w_start)
            span = {
                "trace_id": ctx.trace_id,
                "span_id": ctx.span_id,
                "parent_id": ctx.parent_id,
                "name": ("message.dispatch" if forwarded
                         else "message.publish"),
                "node": self.node,
                "start_ns": int(start * 1e9),
                "end_ns": int(w_end * 1e9),
                "mid": msg.mid.hex(),
                "attrs": {
                    "topic": msg.topic,
                    "qos": msg.qos,
                    "deliveries": counts[i],
                    "n_clients": n_clients,
                    "source": source,
                    "path": path,
                    "breaker_open": breaker,
                },
                "events": stage_events + fp_events,
            }
            if clients is not None:
                # delivering client ids for this sampled message
                # (recorded by the columns dispatch ONLY for runs that
                # carried a sampled message — capped so a fanout-10k
                # span stays bounded)
                cl = clients.get(id(msg))
                if cl:
                    span["attrs"]["clients"] = cl[:32]
                    span["attrs"]["clients_total"] = len(cl)
            self.emit(span)

    # ------------------------------------------------------ forwards

    def begin_forward(self, ctx: TraceContext, kind: str,
                      target: str, topic: str = "",
                      mid: str = "") -> PendingForward:
        """Open a forward span at an egress seam (cluster forward,
        link egress).  The returned handle is closed by whatever
        learns the outcome; its span id is what the peer's dispatch
        span parents to."""
        self.stats["forwards"] += 1
        span = {
            "trace_id": ctx.trace_id,
            "span_id": self.sampler.span_id(),
            "parent_id": ctx.span_id,
            "name": kind,
            "node": self.node,
            "start_ns": time.time_ns(),
            "end_ns": 0,
            "mid": mid,
            "attrs": {"target": target, "topic": topic},
            "events": [],
        }
        return PendingForward(self, span)

    def forward_copy(self, msg, ctx: TraceContext, target: str):
        """One traced forwarded copy of ``msg`` for ``target``: opens a
        ``message.forward`` span, injects (trace_id, forward span id)
        into a COPY of the properties (the local original — retained
        copies, detached-queue bakes, redeliveries — stays untouched),
        and rides the pending span on the clone for the flush loop to
        close.  Only sampled messages ever reach this."""
        import dataclasses

        pend = self.begin_forward(
            ctx, "message.forward", target,
            topic=msg.topic, mid=msg.mid.hex(),
        )
        props = dict(msg.properties) if msg.properties else {}
        inject_props(props, ctx.trace_id, pend.span_id)
        clone = dataclasses.replace(msg, properties=props)
        clone._trace_fwd = pend
        return clone

    # --------------------------------------------------------- emit

    def emit(self, span: Dict) -> None:
        self.store.add(span)
        exp = self.on_export
        if exp is not None:
            try:
                exp(span)
            except Exception:
                pass  # export must never affect dispatch


def _failpoint_events(w_start: float, w_end: float) -> List[Dict]:
    """Failpoint fires that landed inside the window, as span events —
    chaos runs attribute an anomalous window to the fault that caused
    it without correlating logs by hand."""
    from . import failpoints

    if not failpoints.RECENT_FIRES:
        return []
    out = []
    for ts, name, action, key in list(failpoints.RECENT_FIRES):
        if w_start <= ts <= w_end:
            out.append({
                "name": f"failpoint.{name}",
                "ts_ns": int(ts * 1e9),
                "attrs": {"action": action, "key": key or ""},
            })
    return out


# ------------------------------------------------------ perfetto export

def chrome_trace(spans: Sequence[Dict]) -> Dict[str, object]:
    """Render span dicts — one node's store or several nodes' dumps
    concatenated — as Chrome trace-event JSON (Perfetto-loadable):

      * one PROCESS per distinct ``node`` (explicit ``process_name``
        metadata, stable pids), so merged multi-node/multi-worker
        timelines keep each broker on its own row group;
      * one thread track per (node, trace), named by the trace id;
      * each span is a complete ("X") event; its span events ride as
        instant ("i") events on the same track;
      * every forward hop gets a FLOW (s→f) from the forward span to
        the remote span that parents to it — the visual thread
        connecting a publish on node A to its delivery on node B.

    Timestamps are exported relative to the earliest span (float64 µs
    at absolute epoch magnitude quantizes ~0.25 µs — same fix as the
    profiler's export)."""
    spans = [s for s in spans if s.get("end_ns")]
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    nodes: List[str] = []
    for s in spans:
        n = s.get("node", "?")
        if n not in nodes:
            nodes.append(n)
    pid_of = {n: i + 1 for i, n in enumerate(nodes)}
    epoch_ns = min(s["start_ns"] for s in spans)
    events: List[Dict[str, object]] = []
    for n, pid in pid_of.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"emqx_tpu {n}"},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": pid,
            "tid": 0, "args": {"sort_index": pid},
        })
    tids: Dict[Tuple[str, str], int] = {}
    named: set = set()
    # forward spans indexed by span id: flow sources
    fwd = {
        s["span_id"]: s for s in spans
        if s["name"] in ("message.forward", "link.forward")
    }
    for s in spans:
        node = s.get("node", "?")
        pid = pid_of[node]
        key = (node, s["trace_id"])
        tid = tids.setdefault(key, len(tids) + 1)
        if key not in named:
            named.add(key)
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid,
                "args": {"name": f"trace {s['trace_id'][:8]}"},
            })
        ts = (s["start_ns"] - epoch_ns) / 1e3
        dur = max((s["end_ns"] - s["start_ns"]) / 1e3, 0.001)
        events.append({
            "name": s["name"], "ph": "X", "pid": pid, "tid": tid,
            "ts": ts, "dur": dur,
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_id": s.get("parent_id") or "",
                "mid": s.get("mid", ""),
                **s.get("attrs", {}),
            },
        })
        for ev in s.get("events", ()):
            events.append({
                "name": ev["name"], "ph": "i", "pid": pid, "tid": tid,
                "ts": (ev["ts_ns"] - epoch_ns) / 1e3, "s": "t",
                "args": dict(ev.get("attrs", ())),
            })
    # flow events: forward span -> the (possibly remote) span that
    # parents to it.  53-bit ids keep JSON number-safe.
    for s in spans:
        parent = s.get("parent_id")
        src = fwd.get(parent) if parent else None
        if src is None or src is s:
            continue
        flow_id = int(parent[:13], 16)
        src_pid = pid_of[src.get("node", "?")]
        src_tid = tids[(src.get("node", "?"), src["trace_id"])]
        dst_pid = pid_of[s.get("node", "?")]
        dst_tid = tids[(s.get("node", "?"), s["trace_id"])]
        src_ts = (src["start_ns"] - epoch_ns) / 1e3
        events.append({
            "name": "hop", "ph": "s", "cat": "forward", "id": flow_id,
            "pid": src_pid, "tid": src_tid, "ts": src_ts,
        })
        events.append({
            "name": "hop", "ph": "f", "bp": "e", "cat": "forward",
            "id": flow_id, "pid": dst_pid, "tid": dst_tid,
            "ts": max((s["start_ns"] - epoch_ns) / 1e3, src_ts),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
