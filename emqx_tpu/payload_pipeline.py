"""Payload pipeline: message transformation + schema validation.

The `emqx_message_transformation` + `emqx_schema_validation` slice
(/root/reference/apps/emqx_message_transformation,
apps/emqx_schema_validation; hookpoints 'message.transformation_failed'
and 'schema.validation_failed', emqx_hookpoints.erl:63-64): both hook
ahead of routing on ``message.publish`` — transformations rewrite
topic/payload fields, validations check JSON payloads against JSON
Schema and drop or disconnect on failure.  Order matches the
reference: transformation first, then validation.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import topic as T
from .hooks import STOP_WITH
from .message import Message

log = logging.getLogger("emqx_tpu.pipeline")


@dataclass
class Transformation:
    """Set topic or payload fields from ``${...}`` templates rendered
    against the rule-engine environment (payload.*, topic, clientid)."""

    name: str
    topics: List[str]
    # operations: dotted target -> template; targets: "topic" or
    # "payload.<field>"; a non-template value is assigned literally
    operations: Dict[str, Any] = field(default_factory=dict)
    failure_action: str = "drop"  # drop | ignore


@dataclass
class Validation:
    name: str
    topics: List[str]
    schema: Dict[str, Any]  # JSON Schema
    failure_action: str = "drop"  # drop | disconnect | ignore
    _validator: Any = None

    def validator(self):
        if self._validator is None:
            import jsonschema

            self._validator = jsonschema.Draft202012Validator(self.schema)
        return self._validator


class PayloadPipeline:
    def __init__(self, broker) -> None:
        self.broker = broker
        self.transformations: List[Transformation] = []
        self.validations: List[Validation] = []
        # one hook, ordered after rewrite (90) and delayed (100), before
        # the trace tap and rule dispatch
        broker.hooks.add("message.publish", self._on_publish, priority=80)

    # ------------------------------------------------------ management

    def add_transformation(self, t: Transformation) -> None:
        for flt in t.topics:
            T.validate_filter(flt)
        self.transformations.append(t)

    def add_validation(self, v: Validation) -> None:
        for flt in v.topics:
            T.validate_filter(flt)
        v.validator()  # compile now: a bad schema fails registration
        self.validations.append(v)

    def remove(self, name: str) -> bool:
        n0 = len(self.transformations) + len(self.validations)
        self.transformations = [
            t for t in self.transformations if t.name != name
        ]
        self.validations = [v for v in self.validations if v.name != name]
        return len(self.transformations) + len(self.validations) != n0

    def info(self) -> List[Dict]:
        return [
            {"name": t.name, "kind": "transformation", "topics": t.topics}
            for t in self.transformations
        ] + [
            {"name": v.name, "kind": "validation", "topics": v.topics}
            for v in self.validations
        ]

    # ------------------------------------------------------------ hook

    def _matches(self, topics: List[str], topic: str) -> bool:
        return any(T.match(topic, flt) for flt in topics)

    def _on_publish(self, msg: Message):
        if msg.sys or not (self.transformations or self.validations):
            return None
        out = msg
        for t in self.transformations:
            if not self._matches(t.topics, out.topic):
                continue
            try:
                out = self._apply_transformation(t, out)
            except Exception as exc:
                self.broker.metrics.inc("messages.transformation_failed")
                self.broker.hooks.run(
                    "message.transformation_failed", out, t.name, str(exc)
                )
                if t.failure_action == "drop":
                    return STOP_WITH(None)
        for v in self.validations:
            if not self._matches(v.topics, out.topic):
                continue
            err = self._validate(v, out)
            if err is not None:
                self.broker.metrics.inc("messages.validation_failed")
                self.broker.hooks.run(
                    "schema.validation_failed", out, v.name, err
                )
                if v.failure_action == "disconnect" and out.from_client:
                    ch = self.broker.cm.channel(out.from_client)
                    if ch is not None:
                        ch.close("validation_failed")
                if v.failure_action in ("drop", "disconnect"):
                    return STOP_WITH(None)
        return out if out is not msg else None

    def _apply_transformation(
        self, t: Transformation, msg: Message
    ) -> Message:
        from .rules.engine import render_template
        from .rules.runtime import build_env

        env = build_env(msg)
        touches_payload = any(
            target == "payload" or target.startswith("payload.")
            for target in t.operations
        )
        payload = None
        if touches_payload:
            # only payload-editing operations need (and re-encode) JSON;
            # a non-JSON payload is a transformation FAILURE, never a
            # silent replacement with {}
            payload = json.loads(msg.payload.decode())
            if not isinstance(payload, dict):
                payload = {"value": payload}
        new_topic = msg.topic
        for target, template in t.operations.items():
            value = (
                render_template(template, env)
                if isinstance(template, str) and "${" in template
                else template
            )
            if target == "topic":
                new_topic = str(value)
            elif target == "payload":
                payload = value
            elif target.startswith("payload."):
                payload[target[len("payload."):]] = value
            else:
                raise ValueError(f"unknown transformation target {target}")
        return Message(
            topic=new_topic,
            payload=json.dumps(payload).encode()
            if touches_payload
            else msg.payload,
            qos=msg.qos,
            retain=msg.retain,
            from_client=msg.from_client,
            from_username=msg.from_username,
            mid=msg.mid,
            timestamp=msg.timestamp,
            properties=dict(msg.properties),
            headers=dict(msg.headers),
        )

    def _validate(self, v: Validation, msg: Message) -> Optional[str]:
        try:
            payload = json.loads(msg.payload.decode())
        except (ValueError, UnicodeDecodeError) as exc:
            return f"payload is not JSON: {exc}"
        errors = sorted(
            v.validator().iter_errors(payload), key=lambda e: e.path
        )
        if errors:
            return "; ".join(e.message for e in errors[:3])
        return None
