"""Resource framework: buffered, health-checked sinks for rule actions.

A compact analogue of `emqx_resource` (/root/reference/apps/
emqx_resource/src/emqx_resource.erl:169-253 behavior callbacks;
emqx_resource_manager.erl health state machine;
emqx_resource_buffer_worker.erl replayq buffering): every external IO
target is a Resource with start/stop/query/health callbacks, fronted by
a BufferWorker that absorbs bursts and outages — queries queue in a
bounded buffer, failures retry with backoff while the resource is
marked disconnected, and nothing is lost within the buffer bound.

`HttpSink` is the built-in HTTP action target (the emqx_bridge_http
role) using aiohttp.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Dict, Optional

from . import failpoints
from .aio import cancel_and_wait

log = logging.getLogger("emqx_tpu.resources")

CONNECTING = "connecting"
CONNECTED = "connected"
DISCONNECTED = "disconnected"


class Resource:
    """Callback behavior (emqx_resource.erl:169-253)."""

    async def on_start(self) -> None: ...

    async def on_stop(self) -> None: ...

    async def on_query(self, query: Any) -> None:
        """Deliver one query; raise on failure (triggers retry)."""
        raise NotImplementedError

    async def health_check(self) -> bool:
        return True


class HttpSink(Resource):
    """POST each query's body to a URL (emqx_bridge_http essentials)."""

    def __init__(
        self,
        url: str,
        method: str = "POST",
        headers: Optional[Dict[str, str]] = None,
        timeout: float = 5.0,
    ) -> None:
        self.url = url
        self.method = method
        self.headers = dict(headers or {})
        self.timeout = timeout
        self._session = None

    async def on_start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout)
        )

    async def on_stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def on_query(self, query: Any) -> None:
        body = query if isinstance(query, (bytes, str)) else None
        json_body = None if body is not None else query
        async with self._session.request(
            self.method,
            self.url,
            data=body,
            json=json_body,
            headers=self.headers,
        ) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"http sink status {resp.status}")

    async def health_check(self) -> bool:
        try:
            async with self._session.head(
                self.url, headers=self.headers
            ) as resp:
                return resp.status < 500
        except Exception:
            return False


class BufferWorker:
    """Bounded replay buffer + retrying drain loop per resource
    (emqx_resource_buffer_worker.erl): queries survive sink outages up
    to ``max_buffer``; beyond it the OLDEST drops (counted)."""

    def __init__(
        self,
        resource: Resource,
        max_buffer: int = 10_000,
        max_retries: Optional[int] = None,
        retry_base: float = 0.05,
        retry_cap: float = 5.0,
        health_interval: float = 1.0,
    ) -> None:
        self.resource = resource
        self.name = ""  # resource_id when owned by a ResourceManager
        self.max_buffer = max_buffer
        self.max_retries = max_retries
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        self.health_interval = health_interval
        self.status = CONNECTING
        self.stats = {
            "matched": 0,
            "success": 0,
            "failed": 0,
            "dropped": 0,
            "retried": 0,
        }
        self._buf: deque = deque()
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------- lifecycle

    async def start(self) -> None:
        await self.resource.on_start()
        self.status = CONNECTED if await self._health() else DISCONNECTED
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            # cancel_and_wait: the drain loop's wait_for can swallow a
            # cancel that lands as the wake future resolves (bpo-37658)
            await cancel_and_wait(self._task)
            self._task = None
        await self.resource.on_stop()

    async def _health(self) -> bool:
        try:
            return await self.resource.health_check()
        except Exception:
            return False

    # alarm hook, wired by the ResourceManager when a broker owns this
    # worker (the reference raises resource_down alarms the same way)
    on_status_alarm = None

    def _alarm(self, down: bool) -> None:
        if self.on_status_alarm is not None:
            try:
                self.on_status_alarm(down)
            except Exception:
                pass

    def _set_status(self, new: str) -> None:
        """EVERY status flip goes through here so the alarm fires on
        the drain path too (a sink failing under sustained traffic
        never reaches the idle probe)."""
        if new == self.status:
            return
        self.status = new
        if new == DISCONNECTED:
            self._alarm(True)
        elif new == CONNECTED:
            self._alarm(False)

    # --------------------------------------------------------- intake

    def enqueue(self, query: Any) -> bool:
        """Queue one query (non-blocking; called from rule actions).
        Returns False when the buffer had to drop its oldest entry."""
        self.stats["matched"] += 1
        ok = True
        if len(self._buf) >= self.max_buffer:
            self._buf.popleft()
            self.stats["dropped"] += 1
            ok = False
        self._buf.append(query)
        self._wake.set()
        return ok

    def __len__(self) -> int:
        return len(self._buf)

    # ---------------------------------------------------------- drain

    async def _run(self) -> None:
        backoff = self.retry_base
        retries = 0
        while True:
            if not self._buf:
                self._wake.clear()
                try:
                    await asyncio.wait_for(
                        self._wake.wait(), self.health_interval
                    )
                except asyncio.TimeoutError:
                    # periodic probe in EVERY state (the reference's
                    # resource manager health-checks while connected
                    # too — a silently dead sink must flip to
                    # disconnected before traffic piles into it, not
                    # when the next query fails)
                    healthy = await self._health()
                    self._set_status(
                        CONNECTED if healthy else DISCONNECTED
                    )
                    continue
            # batching sinks (Kafka): drain up to resource.max_batch
            # queries into one on_query_batch call, which returns how
            # many it consumed — a partial consume leaves the tail at
            # the head for the retry path (the reference's buffer
            # workers batch the same way)
            n_batch = getattr(self.resource, "max_batch", 1)
            query = self._buf[0]  # keep at head until delivered
            try:
                if failpoints.enabled:
                    # chaos seam INSIDE the try: an injected error
                    # rides the worker's real retry/backoff path with
                    # the query still at the buffer head (no loss)
                    await failpoints.evaluate_async(
                        "resource.buffer.query",
                        key=self.name or type(self.resource).__name__,
                    )
                if n_batch > 1 and hasattr(
                    self.resource, "on_query_batch"
                ):
                    batch = [
                        self._buf[i]
                        for i in range(min(n_batch, len(self._buf)))
                    ]
                    done = await self.resource.on_query_batch(batch)
                    done = len(batch) if done is None else int(done)
                    for _ in range(done):
                        self._buf.popleft()
                    self.stats["success"] += done
                    if done < len(batch):
                        raise RuntimeError(
                            f"sink consumed {done}/{len(batch)}"
                        )
                else:
                    await self.resource.on_query(query)
                    self._buf.popleft()
                    self.stats["success"] += 1
                self._set_status(CONNECTED)
                backoff = self.retry_base
                retries = 0
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self._set_status(DISCONNECTED)
                self.stats["retried"] += 1
                retries += 1
                if (
                    self.max_retries is not None
                    and retries > self.max_retries
                ):
                    self._buf.popleft()
                    self.stats["failed"] += 1
                    retries = 0
                    backoff = self.retry_base  # next query starts fresh
                    log.warning(
                        "sink query dropped after %d retries: %s",
                        self.max_retries,
                        exc,
                    )
                    continue
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.retry_cap)


class ResourceManager:
    """Registry of named resources and their buffer workers
    (emqx_resource_manager's lifecycle role)."""

    def __init__(self, alarms=None) -> None:
        self._workers: Dict[str, BufferWorker] = {}
        self.alarms = alarms  # broker AlarmRegistry (optional)

    async def create(
        self, resource_id: str, resource: Resource, **worker_kw
    ) -> BufferWorker:
        await self.remove(resource_id)
        worker = BufferWorker(resource, **worker_kw)
        worker.name = resource_id
        if self.alarms is not None:
            def status_alarm(down: bool, rid=resource_id):
                if down:
                    self.alarms.activate(
                        f"resource_down:{rid}",
                        details={"resource": rid},
                        message=f"resource {rid} health check failing",
                    )
                else:
                    self.alarms.deactivate(f"resource_down:{rid}")
            worker.on_status_alarm = status_alarm
        await worker.start()
        self._workers[resource_id] = worker
        return worker

    def get(self, resource_id: str) -> Optional[BufferWorker]:
        return self._workers.get(resource_id)

    async def remove(self, resource_id: str) -> bool:
        worker = self._workers.pop(resource_id, None)
        if worker is None:
            return False
        # a deleted resource must not leave its down-alarm behind
        worker._alarm(False)
        await worker.stop()
        return True

    async def stop_all(self) -> None:
        for rid in list(self._workers):
            await self.remove(rid)

    def info(self) -> Dict[str, Dict]:
        return {
            rid: {
                "status": w.status,
                "buffered": len(w),
                **w.stats,
            }
            for rid, w in self._workers.items()
        }
