"""Resource framework: buffered, health-checked sinks for rule actions.

A compact analogue of `emqx_resource` (/root/reference/apps/
emqx_resource/src/emqx_resource.erl:169-253 behavior callbacks;
emqx_resource_manager.erl health state machine;
emqx_resource_buffer_worker.erl replayq buffering): every external IO
target is a Resource with start/stop/query/health callbacks, fronted by
a BufferWorker that absorbs bursts and outages — queries queue in a
bounded buffer, failures retry with backoff while the resource is
marked disconnected, and nothing is lost within the buffer bound.

`HttpSink` is the built-in HTTP action target (the emqx_bridge_http
role) using aiohttp.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from . import failpoints
from .aio import cancel_and_wait
from .observability import Histogram

log = logging.getLogger("emqx_tpu.resources")

CONNECTING = "connecting"
CONNECTED = "connected"
DISCONNECTED = "disconnected"

# an olp-deferred flush still has a hard age ceiling: the linger cap
# stretches by at most this factor while the ladder is at L1+
DEFER_AGE_FACTOR = 4.0


def _qsize(q: Any) -> int:
    """Approximate in-buffer byte cost of one query (drives the
    ``batch_bytes`` flush threshold; exactness doesn't matter, only
    monotonic accounting that returns the same figure on enqueue and
    dequeue)."""
    if isinstance(q, (bytes, str)):
        return len(q)
    if isinstance(q, tuple):
        return 16 + sum(
            len(x) for x in q if isinstance(x, (bytes, str))
        )
    return 64


class Resource:
    """Callback behavior (emqx_resource.erl:169-253)."""

    async def on_start(self) -> None: ...

    async def on_stop(self) -> None: ...

    async def on_query(self, query: Any) -> None:
        """Deliver one query; raise on failure (triggers retry)."""
        raise NotImplementedError

    async def health_check(self) -> bool:
        return True


class HttpSink(Resource):
    """POST each query's body to a URL (emqx_bridge_http essentials)."""

    def __init__(
        self,
        url: str,
        method: str = "POST",
        headers: Optional[Dict[str, str]] = None,
        timeout: float = 5.0,
    ) -> None:
        self.url = url
        self.method = method
        self.headers = dict(headers or {})
        self.timeout = timeout
        self._session = None

    async def on_start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout)
        )

    async def on_stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def on_query(self, query: Any) -> None:
        body = query if isinstance(query, (bytes, str)) else None
        json_body = None if body is not None else query
        async with self._session.request(
            self.method,
            self.url,
            data=body,
            json=json_body,
            headers=self.headers,
        ) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"http sink status {resp.status}")

    async def health_check(self) -> bool:
        try:
            async with self._session.head(
                self.url, headers=self.headers
            ) as resp:
                return resp.status < 500
        except Exception:
            return False


class BufferWorker:
    """Bounded replay buffer + retrying drain loop per resource
    (emqx_resource_buffer_worker.erl): queries survive sink outages up
    to ``max_buffer``; beyond it the OLDEST drops (counted).

    Micro-batching (PR 20, the window-shaped egress): with
    ``batch_age > 0`` the drain loop lingers until a count
    (``batch_records``), byte (``batch_bytes``) or age threshold is
    crossed before flushing — so a window of rule actions leaves as
    ONE ``on_query_batch`` call instead of per-record round-trips.
    All three default OFF (immediate drain, the pre-PR behavior).
    An olp L1+ episode stretches the age linger (``defer_flush``
    callable, capped at ``DEFER_AGE_FACTOR``x) — flushes defer before
    any QoS0 shed, and nothing is lost: queries stay buffered.

    Circuit breaker (``breaker_threshold`` consecutive failures):
    while open, the drain loop parks — buffered batches are retained
    for replay, intake keeps absorbing up to the bound — and the
    periodic health probe re-closes it.  Edges fire
    ``on_breaker_edge`` (ResourceManager wires the $SYS alarm +
    flight-recorder event)."""

    def __init__(
        self,
        resource: Resource,
        max_buffer: int = 10_000,
        max_retries: Optional[int] = None,
        retry_base: float = 0.05,
        retry_cap: float = 5.0,
        health_interval: float = 1.0,
        batch_records: int = 0,
        batch_bytes: int = 0,
        batch_age: float = 0.0,
        breaker_threshold: int = 0,
        defer_flush: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.resource = resource
        self.name = ""  # resource_id when owned by a ResourceManager
        self.max_buffer = max_buffer
        self.max_retries = max_retries
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        self.health_interval = health_interval
        self.batch_records = batch_records
        self.batch_bytes = batch_bytes
        self.batch_age = batch_age
        self.breaker_threshold = breaker_threshold
        self.defer_flush = defer_flush
        self.status = CONNECTING
        self.breaker_open = False
        self.stats = {
            "matched": 0,
            "success": 0,
            "failed": 0,
            "dropped": 0,
            "retried": 0,
            "batches": 0,
            "flush_deferred": 0,
            "breaker_opens": 0,
        }
        self.batch_hist = Histogram()  # flushed batch sizes
        self._buf: deque = deque()
        self._buf_bytes = 0
        self._oldest_ts = 0.0
        self._defer_noted = False
        self._fail_streak = 0
        self._q_full_edge = False
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------- lifecycle

    async def start(self) -> None:
        await self.resource.on_start()
        self.status = CONNECTED if await self._health() else DISCONNECTED
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            # cancel_and_wait: the drain loop's wait_for can swallow a
            # cancel that lands as the wake future resolves (bpo-37658)
            await cancel_and_wait(self._task)
            self._task = None
        await self.resource.on_stop()

    async def _health(self) -> bool:
        try:
            return await self.resource.health_check()
        except Exception:
            return False

    # hooks, wired by the ResourceManager when a broker owns this
    # worker (the reference raises resource_down alarms the same way):
    # status alarm, breaker open/close edge, olp flush-deferral count,
    # queue-full edge — all optional, all exception-isolated
    on_status_alarm = None
    on_breaker_edge: Optional[Callable[[bool], None]] = None
    on_flush_deferred: Optional[Callable[[], None]] = None
    on_queue_full: Optional[Callable[[int], None]] = None

    def _alarm(self, down: bool) -> None:
        if self.on_status_alarm is not None:
            try:
                self.on_status_alarm(down)
            except Exception:
                pass

    def _set_status(self, new: str) -> None:
        """EVERY status flip goes through here so the alarm fires on
        the drain path too (a sink failing under sustained traffic
        never reaches the idle probe)."""
        if new == self.status:
            return
        self.status = new
        if new == DISCONNECTED:
            self._alarm(True)
        elif new == CONNECTED:
            self._alarm(False)

    # --------------------------------------------------------- intake

    def enqueue(self, query: Any) -> bool:
        """Queue one query (non-blocking; called from rule actions).
        Returns False when the buffer had to drop its oldest entry."""
        self.stats["matched"] += 1
        if not self._buf:
            self._oldest_ts = time.monotonic()
        ok = True
        if len(self._buf) >= self.max_buffer:
            old = self._buf.popleft()
            self._buf_bytes -= _qsize(old)
            self.stats["dropped"] += 1
            self._note_queue_full(1)
            ok = False
        elif self._q_full_edge and (
            len(self._buf) < self.max_buffer // 2
        ):
            self._q_full_edge = False  # re-arm the edge event
        self._buf.append(query)
        self._buf_bytes += _qsize(query)
        self._wake.set()
        return ok

    def enqueue_batch(self, queries: list) -> int:
        """Queue a whole action window in one call (the batched rule
        egress).  Returns how many OLDEST entries dropped to hold the
        ``max_buffer`` bound (0 = nothing lost)."""
        n = len(queries)
        if not n:
            return 0
        self.stats["matched"] += n
        if not self._buf:
            self._oldest_ts = time.monotonic()
        buf = self._buf
        buf.extend(queries)
        self._buf_bytes += sum(map(_qsize, queries))
        dropped = len(buf) - self.max_buffer
        if dropped > 0:
            for _ in range(dropped):
                old = buf.popleft()
                self._buf_bytes -= _qsize(old)
            self.stats["dropped"] += dropped
            self._note_queue_full(dropped)
        else:
            dropped = 0
            if self._q_full_edge and len(buf) < self.max_buffer // 2:
                self._q_full_edge = False
        self._wake.set()
        return dropped

    def _note_queue_full(self, dropped: int) -> None:
        """Edge-triggered queue-full event (flight recorder feed): one
        event per excursion to the bound, re-armed once the buffer
        drains below half."""
        if not self._q_full_edge:
            self._q_full_edge = True
            if self.on_queue_full is not None:
                try:
                    self.on_queue_full(dropped)
                except Exception:
                    pass

    def __len__(self) -> int:
        return len(self._buf)

    # ---------------------------------------------------------- drain

    def _linger_remaining(self) -> float:
        """Seconds the drain loop should still linger before flushing
        the pending micro-batch (0.0 = flush now).  Count and byte
        thresholds release immediately; otherwise the batch rides
        until ``batch_age`` — stretched (capped) while the olp ladder
        asks sink flushes to defer."""
        if self.batch_age <= 0.0:
            return 0.0
        if self.batch_records and len(self._buf) >= self.batch_records:
            return 0.0
        if self.batch_bytes and self._buf_bytes >= self.batch_bytes:
            return 0.0
        limit = self.batch_age
        if self.defer_flush is not None:
            try:
                if self.defer_flush():
                    limit = self.batch_age * DEFER_AGE_FACTOR
                    if not self._defer_noted:
                        # one deferral event per pending batch
                        self._defer_noted = True
                        self.stats["flush_deferred"] += 1
                        if self.on_flush_deferred is not None:
                            self.on_flush_deferred()
            except Exception:
                pass
        age = time.monotonic() - self._oldest_ts
        return max(0.0, limit - age)

    def _trip_breaker(self, exc: Exception) -> None:
        self.breaker_open = True
        self.stats["breaker_opens"] += 1
        log.warning(
            "sink %s breaker OPEN after %d consecutive failures "
            "(%d queries parked): %s",
            self.name or type(self.resource).__name__,
            self._fail_streak, len(self._buf), exc,
        )
        if self.on_breaker_edge is not None:
            try:
                self.on_breaker_edge(True)
            except Exception:
                pass

    async def _breaker_probe(self) -> None:
        """While the breaker is open the drain loop parks here:
        buffered batches are retained for replay, and a successful
        health probe re-closes the breaker."""
        await asyncio.sleep(self.health_interval)
        if await self._health():
            self.breaker_open = False
            self._fail_streak = 0
            self._set_status(CONNECTED)
            if self.on_breaker_edge is not None:
                try:
                    self.on_breaker_edge(False)
                except Exception:
                    pass
        else:
            self._set_status(DISCONNECTED)

    async def _flush_once(self) -> None:
        """Deliver the buffer head: one query, or — for batching
        sinks — up to ``resource.max_batch`` queries as ONE
        ``on_query_batch`` call, which returns how many it consumed;
        a partial consume leaves the tail at the head for the retry
        path (the reference's buffer workers batch the same way).

        Chaos seams (both INSIDE the caller's try, so injected faults
        ride the real retry/backoff/replay path with every query
        still buffered): ``resource.buffer.query`` per delivery
        attempt, ``resource.batch.flush`` per multi-record flush —
        there, ``drop`` simulates a flush lost in flight (records
        stay at the head and replay; no loss) and ``duplicate``
        delivers the batch twice (at-least-once duplication)."""
        buf = self._buf
        n_batch = getattr(self.resource, "max_batch", 1)
        if failpoints.enabled:
            await failpoints.evaluate_async(
                "resource.buffer.query",
                key=self.name or type(self.resource).__name__,
            )
        if n_batch > 1 and hasattr(self.resource, "on_query_batch"):
            batch = [
                buf[i] for i in range(min(n_batch, len(buf)))
            ]
            if failpoints.enabled:
                act = await failpoints.evaluate_async(
                    "resource.batch.flush",
                    key=self.name or type(self.resource).__name__,
                )
                if act == "drop":
                    raise RuntimeError(
                        "batch flush dropped in flight (failpoint)"
                    )
                if act == "duplicate":
                    await self.resource.on_query_batch(list(batch))
            done = await self.resource.on_query_batch(batch)
            done = len(batch) if done is None else int(done)
            for _ in range(done):
                self._buf_bytes -= _qsize(buf.popleft())
            self.stats["success"] += done
            self.stats["batches"] += 1
            self.batch_hist.record(len(batch))
            if done < len(batch):
                raise RuntimeError(
                    f"sink consumed {done}/{len(batch)}"
                )
        else:
            query = buf[0]  # keep at head until delivered
            await self.resource.on_query(query)
            self._buf_bytes -= _qsize(buf.popleft())
            self.stats["success"] += 1
        # the flushed batch's linger window is spent; the tail (if
        # any) starts a fresh age/deferral budget
        self._oldest_ts = time.monotonic()
        self._defer_noted = False

    async def _run(self) -> None:
        backoff = self.retry_base
        retries = 0
        while True:
            if not self._buf:
                self._wake.clear()
                try:
                    await asyncio.wait_for(
                        self._wake.wait(), self.health_interval
                    )
                except asyncio.TimeoutError:
                    # periodic probe in EVERY state (the reference's
                    # resource manager health-checks while connected
                    # too — a silently dead sink must flip to
                    # disconnected before traffic piles into it, not
                    # when the next query fails)
                    healthy = await self._health()
                    self._set_status(
                        CONNECTED if healthy else DISCONNECTED
                    )
                    continue
            if self.breaker_open:
                await self._breaker_probe()
                continue
            rem = self._linger_remaining()
            if rem > 0.0:
                # micro-batch linger: wake early if intake crosses a
                # count/byte threshold, else sleep out the age budget
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), rem)
                except asyncio.TimeoutError:
                    pass
                continue
            try:
                await self._flush_once()
                self._set_status(CONNECTED)
                backoff = self.retry_base
                retries = 0
                self._fail_streak = 0
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self._set_status(DISCONNECTED)
                self.stats["retried"] += 1
                retries += 1
                self._fail_streak += 1
                if (
                    self.breaker_threshold
                    and self._fail_streak >= self.breaker_threshold
                    and not self.breaker_open
                ):
                    # park instead of hot-retrying a dead sink; the
                    # buffered queries replay after the probe re-close
                    self._trip_breaker(exc)
                    retries = 0
                    backoff = self.retry_base
                    continue
                if (
                    self.max_retries is not None
                    and retries > self.max_retries
                ):
                    self._buf_bytes -= _qsize(self._buf.popleft())
                    self.stats["failed"] += 1
                    retries = 0
                    backoff = self.retry_base  # next query starts fresh
                    log.warning(
                        "sink query dropped after %d retries: %s",
                        self.max_retries,
                        exc,
                    )
                    continue
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.retry_cap)


class ResourceManager:
    """Registry of named resources and their buffer workers
    (emqx_resource_manager's lifecycle role).  When a broker owns the
    manager it wires ``alarms``/``metrics``/``flight``/``olp`` so
    every worker's breaker edges raise $SYS alarms + flight events,
    flush deferrals count under the olp ladder, and queue-full
    excursions land in the black box."""

    def __init__(self, alarms=None) -> None:
        self._workers: Dict[str, BufferWorker] = {}
        self.alarms = alarms  # broker AlarmRegistry (optional)
        self.metrics = None  # broker MetricsRegistry (optional)
        self.flight = None  # broker FlightRecorder (optional)
        self.olp = None  # broker OverloadProtection (optional)

    async def create(
        self, resource_id: str, resource: Resource, **worker_kw
    ) -> BufferWorker:
        await self.remove(resource_id)
        worker = BufferWorker(resource, **worker_kw)
        worker.name = resource_id
        if self.alarms is not None:
            def status_alarm(down: bool, rid=resource_id):
                if down:
                    self.alarms.activate(
                        f"resource_down:{rid}",
                        details={"resource": rid},
                        message=f"resource {rid} health check failing",
                    )
                else:
                    self.alarms.deactivate(f"resource_down:{rid}")
            worker.on_status_alarm = status_alarm

        def breaker_edge(opened: bool, rid=resource_id):
            if self.alarms is not None:
                if opened:
                    self.alarms.activate(
                        f"sink_breaker:{rid}",
                        details={"resource": rid},
                        message=(
                            f"sink {rid} circuit breaker open "
                            "(batches parked for replay)"
                        ),
                    )
                else:
                    self.alarms.deactivate(f"sink_breaker:{rid}")
            if self.flight is not None:
                self.flight.breaker_edge(opened, {"sink": rid})
        worker.on_breaker_edge = breaker_edge

        def flush_deferred():
            if self.metrics is not None:
                self.metrics.inc("olp.deferred.sink_flush")
        worker.on_flush_deferred = flush_deferred

        def queue_full(dropped: int, rid=resource_id):
            if self.flight is not None:
                self.flight.note(
                    "sink_queue_full", sink=rid, dropped=dropped
                )
        worker.on_queue_full = queue_full

        if worker.defer_flush is None and self.olp is not None:
            worker.defer_flush = (
                lambda: self.olp.defer_sink_flush
            )
        await worker.start()
        self._workers[resource_id] = worker
        return worker

    def get(self, resource_id: str) -> Optional[BufferWorker]:
        return self._workers.get(resource_id)

    async def remove(self, resource_id: str) -> bool:
        worker = self._workers.pop(resource_id, None)
        if worker is None:
            return False
        # a deleted resource must not leave its alarms behind
        worker._alarm(False)
        if worker.breaker_open and worker.on_breaker_edge is not None:
            try:
                worker.on_breaker_edge(False)
            except Exception:
                pass
        await worker.stop()
        return True

    async def stop_all(self) -> None:
        for rid in list(self._workers):
            await self.remove(rid)

    def info(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for rid, w in self._workers.items():
            snap = w.batch_hist.snapshot()
            out[rid] = {
                "status": w.status,
                "buffered": len(w),
                "breaker_open": w.breaker_open,
                "batch_size": {
                    "count": snap.count,
                    "p50": snap.percentile(50),
                    "p95": snap.percentile(95),
                    "p99": snap.percentile(99),
                },
                **w.stats,
            }
        return out

    def summary(self) -> Dict[str, int]:
        """Node-info roll-up across every sink worker."""
        ws = self._workers.values()
        return {
            "sinks": len(self._workers),
            "buffered": sum(len(w) for w in ws),
            "batches": sum(w.stats["batches"] for w in ws),
            "flush_deferred": sum(
                w.stats["flush_deferred"] for w in ws
            ),
            "breakers_open": sum(1 for w in ws if w.breaker_open),
        }
