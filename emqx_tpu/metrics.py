"""Named counters + gauges.

The reference keeps a fixed-slot `counters` array referenced from
persistent_term (`emqx_metrics`, /root/reference/apps/emqx/src/
emqx_metrics.erl:152-356) so hot-path increments are lock-free.  The
Python analogue: a flat list of ints indexed by a frozen name->slot map
(attribute lookups hoisted by callers via ``counter(name)`` handles).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# metric names mirror the reference's ?BYTES_METRICS / ?PACKET_METRICS /
# ?MESSAGE_METRICS tables (emqx_metrics.erl:45-150)
METRICS = (
    "bytes.received",
    "bytes.sent",
    "packets.received",
    "packets.sent",
    "packets.connect.received",
    "packets.connack.sent",
    "packets.publish.received",
    "packets.publish.sent",
    "packets.publish.dropped",
    "packets.publish.error",
    "packets.publish.auth_error",
    "packets.puback.received",
    "packets.puback.sent",
    "packets.pubrec.received",
    "packets.pubrec.sent",
    "packets.pubrel.received",
    "packets.pubrel.sent",
    "packets.pubcomp.received",
    "packets.pubcomp.sent",
    "packets.subscribe.received",
    "packets.suback.sent",
    "packets.subscribe.error",
    "packets.subscribe.auth_error",
    "packets.unsubscribe.received",
    "packets.unsuback.sent",
    "packets.pingreq.received",
    "packets.pingresp.sent",
    "packets.disconnect.received",
    "packets.disconnect.sent",
    "packets.auth.received",
    "messages.received",
    "messages.sent",
    "messages.qos0.received",
    "messages.qos0.sent",
    "messages.qos1.received",
    "messages.qos1.sent",
    "messages.qos2.received",
    "messages.qos2.sent",
    "messages.publish",
    "messages.delivered",
    "messages.acked",
    "messages.dropped",
    "messages.dropped.no_subscribers",
    "messages.dropped.await_pubrel_timeout",
    "messages.dropped.expired",
    "messages.dropped.queue_full",
    "messages.forward",
    "messages.forward.failed",
    "messages.forward.received",
    "messages.forward.dropped",
    "messages.forward.retx",
    "messages.forward.dup",
    "messages.retained",
    "cluster.nodes.down",
    "cluster.forward.breaker.open",
    "delivery.dropped",
    "delivery.dropped.no_local",
    "delivery.dropped.too_large",
    "delivery.dropped.queue_full",
    "delivery.dropped.expired",
    "delivery.dropped.olp_shed",
    "delivery.dropped.out_buffer",
    "messages.dropped.olp_shed",
    "olp.level.changed",
    "olp.deferred.resume",
    "olp.deferred.retained",
    "olp.deferred.rebuild",
    "olp.deferred.sink_flush",
    "olp.dropped.retained",
    "olp.refused.connect",
    "olp.shed.publish_qos0",
    "olp.killed.slow_subs",
    "session.created",
    "session.resumed",
    "session.resume.parked",
    "session.resume.busy",
    "session.resume.foreign_shard",
    "session.replay.windows",
    "session.replay.messages",
    "ds.sync.count",
    "ds.sync.errors",
    "ds.storage.corrupt_records",
    "ds.meta.corruption",
    "session.takenover",
    "session.discarded",
    "session.terminated",
    "client.connect",
    "client.connack",
    "client.connected",
    "client.disconnected",
    "client.authenticate",
    "client.auth.anonymous",
    "client.authorize",
    "authorization.allow",
    "authorization.deny",
    "rules.matched",
    "actions.success",
    "actions.failed",
    "messages.publish.error",
    "messages.delayed",
    "messages.validation_failed",
    "messages.transformation_failed",
    "session.imported",
    "session.purged",
    "session.replica_restored",
    "session.replica_merged",
    "session.takeover.requested",
    "client.evicted",
    "connection.congested",
    "connection.rate_limited",
    "engine.breaker.trip",
    "engine.breaker.clear",
    "ds.meta.rebuild",
    "cluster_link.ingress",
    "cluster_link.egress",
    "bridge.ingress",
    "bridge.egress",
    # flight recorder (flightrec.py)
    "flight.triggers",
    "flight.triggers.suppressed",
    "flight.dumps",
    "flight.dump.errors",
    "flight.remote_requests",
    # shared match service, service-side registry (ops/matchsvc.py)
    "matchsvc.windows",
    "matchsvc.topics",
    "matchsvc.decides",
    "matchsvc.route_ops",
    "matchsvc.errors",
    "matchsvc.flight_relayed",
    # per-worker shm window ring (broker/shmring.py via matchclient)
    "multicore.ring.full",
    "multicore.ring.oversize",
    "multicore.ring.quarantined",
)

# open-ended per-feature counter families (the reference's
# emqx_metrics_worker role: gateways, hook providers, plugins, file
# transfer mint names at runtime).  brokerlint's MET901 accepts any
# literal counter under these prefixes; everything else must have a
# fixed slot above.
EXTRA_METRIC_PREFIXES = (
    "exhook.",
    "gateway.",
    "plugins.",
    "ft.",
)

_SLOT = {name: i for i, name in enumerate(METRICS)}


class Metrics:
    """One counter array; ``inc``/``val`` by name, ``counter`` returns a
    bound fast-path increment callable."""

    def __init__(self) -> None:
        self._c: List[int] = [0] * len(METRICS)
        # names outside the fixed slot registry (per-feature counters
        # like exhook.* — the reference's emqx_metrics_worker role)
        self._extra: Dict[str, int] = {}
        # increments arrive from the event loop AND worker threads
        # (exhook's gRPC pool, the batcher's executor); Python's += is
        # not atomic, so counting is locked (uncontended ~100 ns)
        self._lock = threading.Lock()
        self.start_time = time.time()

    def inc(self, name: str, by: int = 1) -> None:
        i = _SLOT.get(name)
        with self._lock:
            if i is None:
                self._extra[name] = self._extra.get(name, 0) + by
            else:
                self._c[i] += by

    def slots(self, *names: str) -> Tuple[int, ...]:
        """Pre-resolve registry names to slot indices for `inc_slots`
        (hot paths bump several counters per packet; one lock+loop
        beats N inc() calls)."""
        out = []
        for n in names:
            i = _SLOT.get(n)
            if i is None:
                raise KeyError(f"not a registry metric: {n}")
            out.append(i)
        return tuple(out)

    def inc_slots(self, slots: Tuple[int, ...], by: int = 1) -> None:
        c = self._c
        with self._lock:
            for i in slots:
                c[i] += by

    def inc_bulk(self, updates: Dict[str, int]) -> None:
        """Apply a batch of counter deltas under ONE lock acquisition —
        the dispatch window accumulates its per-delivery bookkeeping
        locally and flushes here once per window instead of locking
        per delivery."""
        if not updates:
            return
        c = self._c
        extra = self._extra
        with self._lock:
            for name, by in updates.items():
                i = _SLOT.get(name)
                if i is None:
                    extra[name] = extra.get(name, 0) + by
                else:
                    c[i] += by

    def val(self, name: str) -> int:
        i = _SLOT.get(name)
        return self._extra.get(name, 0) if i is None else self._c[i]

    def counter(self, name: str) -> Callable[[], None]:
        def bump() -> None:
            self.inc(name)

        return bump

    def all(self) -> Dict[str, int]:
        out = {name: self._c[i] for name, i in _SLOT.items()}
        out.update(self._extra)
        return out

    def reset(self) -> None:
        self._c = [0] * len(METRICS)
        self._extra = {}


class Stats:
    """Max-tracking gauges (`emqx_stats`): current + historical max."""

    def __init__(self) -> None:
        self._cur: Dict[str, int] = {}
        self._max: Dict[str, int] = {}

    def set(self, name: str, value: int) -> None:
        self._cur[name] = value
        if value > self._max.get(name + ".max", 0):
            self._max[name + ".max"] = value

    def update_delta(self, name: str, delta: int) -> None:
        self.set(name, self._cur.get(name, 0) + delta)

    def get(self, name: str) -> int:
        return self._cur.get(name, self._max.get(name, 0))

    def all(self) -> Dict[str, int]:
        out = dict(self._cur)
        out.update(self._max)
        return out
