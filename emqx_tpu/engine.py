"""MatchEngine: the subscription-matching core, TPU-accelerated.

Mirrors the reference's v2 router split (/root/reference/apps/emqx/src/
emqx_router.erl:476-525): exact (non-wildcard) filters in an O(1) host
hash map (`?ROUTE_TAB` direct lookup), wildcard filters in an index —
here a device-resident array automaton batch-matched by
`ops.match_kernel`, not an ordered-set skip-scan.

Subscription churn vs XLA immutability (SURVEY §7 "hard parts") is
handled the way `emqx_router_syncer` batches route ops: mutations land
in a host-side *delta* trie immediately (correct from the next match on)
and are folded into a rebuilt device automaton once the delta passes a
threshold.  Deletions are masked out of stale device results by fid.

Any topic the kernel flags (frontier overflow, match-cap overflow, too
deep) is re-matched on the `HostTrie` oracle, so results are always
exact regardless of kernel capacity bounds.
"""

from __future__ import annotations

import threading
from typing import Dict, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import topic as T
from .ops.automaton import Automaton, build_automaton
from .ops.dictionary import TokenDict, encode_topics
from .ops.trie_host import HostTrie


def _pad_batch(tokens, lengths, dollar):
    """Pad the batch to a power-of-two bucket so XLA sees a bounded set
    of shapes (no recompile storm on ragged publish batches)."""
    b = tokens.shape[0]
    bp = 16
    while bp < b:
        bp *= 2
    if bp != b:
        pad = bp - b
        tokens = np.pad(tokens, ((0, pad), (0, 0)), constant_values=-4)
        lengths = np.pad(lengths, (0, pad))  # length 0 => inert row
        dollar = np.pad(dollar, (0, pad), constant_values=True)
    return tokens, lengths, dollar


def make_fid_arr(fids: List[Hashable]) -> np.ndarray:
    """Position -> fid, vectorized-indexable: int64 fast path when every
    fid is an int; object fallback (filled by assignment so tuple fids
    stay 1-D, not broadcast)."""
    if fids and all(type(f) is int for f in fids):
        return np.array(fids, np.int64)
    arr = np.empty(len(fids), object)
    arr[:] = fids
    return arr


class MatchEngine:
    """Mutable filter set with batched matching.

    ``use_device=None`` (default) auto-enables the JAX path when any
    wildcard filters exist; ``False`` forces pure-host matching (the
    reference-equivalent CPU path kept as fallback per BASELINE.json).
    """

    def __init__(
        self,
        max_levels: int = 16,
        f_width: int = 16,
        m_cap: int = 128,
        rebuild_threshold: int = 4096,
        use_device: Optional[bool] = None,
        background_rebuild: bool = False,
    ) -> None:
        self.max_levels = max_levels
        self.f_width = f_width
        self.m_cap = m_cap
        self.rebuild_threshold = rebuild_threshold
        self.use_device = use_device
        self.background_rebuild = background_rebuild
        self._exact: Dict[str, Set[Hashable]] = {}
        self._wild = HostTrie()  # full wildcard set: fallback + rebuild source
        self._delta = HostTrie()  # wildcard filters added since last build
        self._deep = HostTrie()  # filters too deep for the device index
        self._by_fid: Dict[Hashable, str] = {}
        self._deleted: Set[Hashable] = set()  # deleted since last build
        self._tdict = TokenDict()
        self._aut: Optional[Automaton] = None
        self._dev: Optional[Tuple] = None  # device copies of table arrays
        self._base_fids: Set[Hashable] = set()
        # background (double-buffered) rebuild state: the builder thread
        # assembles a new snapshot while matching continues on the live
        # one — the `emqx_router_syncer` no-stop-the-world property
        # (/root/reference/apps/emqx/src/emqx_router_syncer.erl:58)
        self._lock = threading.Lock()
        # serializes host-side mutation vs. the overlay/encode phases of
        # a match running on another thread (the PublishBatcher runs the
        # device step in an executor so the event loop keeps reading
        # sockets); the kernel call itself runs OUTSIDE this lock on an
        # immutable snapshot, so a SUBSCRIBE never waits on the device
        self._mlock = threading.RLock()
        self._building = False
        self._built: Optional[Tuple] = None  # (aut, dev, fid_arr, base_fids)
        self._build_thread: Optional[threading.Thread] = None
        self._pending_inserts: List[Tuple[str, Hashable]] = []
        self._pending_deletes: Set[Hashable] = set()

    # ------------------------------------------------------------- mutation

    def insert(self, flt: str, fid: Hashable) -> None:
        with self._mlock:
            self._insert_locked(flt, fid)

    def _insert_locked(self, flt: str, fid: Hashable) -> None:
        if self._built is not None:
            self._poll_swap()
        T.validate_filter(flt)
        if fid in self._by_fid:
            if self._by_fid[fid] == flt:
                return
            self.delete(fid)
        self._by_fid[fid] = flt
        if T.is_wildcard(flt):
            self._wild.insert(flt, fid)
            ws = T.words(flt)
            body_depth = len(ws) - (1 if ws[-1] == "#" else 0)
            if body_depth > self.max_levels:
                self._deep.insert(flt, fid)
            else:
                # Do NOT clear a tombstone here: if the fid previously
                # carried a *different* filter in the base snapshot, the
                # tombstone is what masks the stale device entry.  The
                # delta trie serves the re-inserted filter until rebuild.
                self._delta.insert(flt, fid)
                if self._building:
                    self._pending_inserts.append((flt, fid))
                if len(self._delta) >= self.rebuild_threshold:
                    if self.background_rebuild:
                        self._start_background_rebuild()
                    else:
                        self.rebuild()
        else:
            self._exact.setdefault(flt, set()).add(fid)

    def delete(self, fid: Hashable) -> bool:
        with self._mlock:
            return self._delete_locked(fid)

    def _delete_locked(self, fid: Hashable) -> bool:
        flt = self._by_fid.pop(fid, None)
        if flt is None:
            return False
        if T.is_wildcard(flt):
            self._wild.delete_id(fid)
            self._delta.delete_id(fid)
            self._deep.delete_id(fid)
            if fid in self._base_fids:
                self._deleted.add(fid)
            if self._building:
                self._pending_deletes.add(fid)
        else:
            ids = self._exact.get(flt)
            if ids is not None:
                ids.discard(fid)
                if not ids:
                    del self._exact[flt]
        return True

    def __len__(self) -> int:
        return len(self._by_fid)

    # -------------------------------------------------------------- rebuild

    def _snapshot_filters(self) -> List[Tuple[Hashable, T.Words]]:
        return [
            (fid, ws)
            for fid, ws in self._wild.filters()
            if fid not in self._deep
        ]

    def _build(
        self, filters, hash_buckets: int = 0, device_put: bool = False
    ):
        aut = build_automaton(
            filters, self._tdict, self.max_levels, hash_buckets=hash_buckets
        )
        fids = [fid for fid, _ in filters]
        dev = None
        if device_put:
            dev = self._device_put(aut)
        return aut, dev, make_fid_arr(fids), set(fids)

    def _device_put(self, aut):
        import jax

        return tuple(jax.device_put(a) for a in aut.device_arrays())

    def rebuild(self, hash_buckets: int = 0) -> None:
        """Fold the delta into a fresh device automaton snapshot
        (synchronous; see ``background_rebuild`` for the no-stall path).

        If a background build is in flight, wait for it first: two
        concurrent builders would interleave TokenDict.add's
        check-then-act and could alias two words onto one token id."""
        t = self._build_thread
        if t is not None and t.is_alive():
            t.join()
        self._poll_swap()
        filters = self._snapshot_filters()
        self._aut, self._dev, self._fid_arr, self._base_fids = self._build(
            filters, hash_buckets=hash_buckets
        )
        self._delta = HostTrie()
        self._deleted = set()

    def _start_background_rebuild(self) -> None:
        with self._lock:
            if self._building:
                return
            self._building = True
            self._pending_inserts = []
            self._pending_deletes = set()
            filters = self._snapshot_filters()

        def work():
            try:
                built = self._build(filters, device_put=True)
            except Exception:  # build failure must not wedge the engine
                import logging

                logging.getLogger("emqx_tpu.engine").exception(
                    "background automaton rebuild failed "
                    "(%d filters); matching continues on the host overlay",
                    len(filters),
                )
                built = ()
            with self._lock:
                self._built = built

        self._build_thread = threading.Thread(
            target=work, name="matchengine-rebuild", daemon=True
        )
        self._build_thread.start()

    def _poll_swap(self) -> None:
        """Adopt a finished background build: O(pending) swap, no stall."""
        if self._built is None:
            return
        with self._lock:
            built = self._built
            self._built = None
            if not built:  # failed build: allow a retrigger
                self._building = False
                return
            self._aut, self._dev, self._fid_arr, self._base_fids = built
            delta = HostTrie()
            for flt, fid in self._pending_inserts:
                if self._by_fid.get(fid) == flt and fid not in self._deep:
                    delta.insert(flt, fid)
            self._delta = delta
            self._deleted = {
                fid for fid in self._pending_deletes if fid in self._base_fids
            }
            self._pending_inserts = []
            self._pending_deletes = set()
            self._building = False

    def warmup(self, max_batch: int = 4096) -> int:
        """Pre-compile the kernel for every power-of-two batch bucket up
        to ``max_batch`` (the `_pad_batch` shape set), so a production
        publish flood never stalls on a first-use XLA compile.  Returns
        the number of buckets warmed (0 when the device path is off)."""
        with self._mlock:
            device_on = (
                self.use_device is not False
                and self._aut is not None
                and self._aut.n_nodes > 1
            )
        if not device_on:
            return 0
        n = 0
        bp = 16
        while bp <= max_batch:
            self.match_batch(["\x00warmup"] * bp)
            n += 1
            bp *= 2
        return n

    def index_stats(self) -> Dict[str, object]:
        return {
            "base": len(self._base_fids),
            "delta": len(self._delta),
            "deep": len(self._deep),
            "exact": sum(len(v) for v in self._exact.values()),
            "deleted": len(self._deleted),
            "building": self._building,
        }

    def _device_tables(self):
        if self._dev is None:
            self._dev = self._device_put(self._aut)
        return self._dev

    # -------------------------------------------------------------- match

    def match(self, topic: str) -> Set[Hashable]:
        return self.match_batch([topic])[0]

    def match_host(self, topic_words: T.Words) -> Set[Hashable]:
        """Pure-host exact match (oracle path)."""
        out = set(self._exact.get(T.join(topic_words), ()))
        out |= self._wild.match_words(topic_words)
        return out

    def _snapshot_refs(self) -> Tuple:
        """Coherent (automaton, device tables, fid array, delta, deep,
        deleted) snapshot; must be captured under ``_mlock`` so a
        concurrent rebuild swap cannot mix generations.  delta/deleted
        belong to the SAME generation as the automaton: a swap landing
        mid-kernel replaces them with (empty) successors folded into the
        new base, and overlaying those against the old base would drop
        every delta-resident subscription for the window."""
        return (
            self._aut,
            self._device_tables(),
            self._fid_arr,
            self._delta,
            self._deep,
            self._deleted,
        )

    def match_batch(self, topics: Sequence[str]) -> List[Set[Hashable]]:
        """Staged so the device step runs lock-free on an immutable
        snapshot: encode/snapshot under the mutation lock, kernel
        outside it, overlay (exact/delta/deep/deleted — possibly newer
        than the snapshot, which only *adds* correctness) under it
        again."""
        words = [T.words(t) for t in topics]
        with self._mlock:
            if self._built is not None:
                self._poll_swap()
            device_on = (
                self.use_device is not False
                and self._aut is not None
                and self._aut.n_nodes > 1
            )
            if device_on:
                snap = self._snapshot_refs()
        if not device_on:
            # per-topic locking: holding _mlock across the whole batch
            # would stall a loop-thread SUBSCRIBE (and with it the
            # entire event loop) for the full window when this runs in
            # the batcher's executor
            out: List[Set[Hashable]] = []
            for ws in words:
                with self._mlock:
                    out.append(self.match_host(ws))
            return out
        rows, gpos, ovf = self._flat_from_snapshot(snap, words)
        with self._mlock:
            return self._overlay(topics, words, rows, gpos, ovf, snap)

    def match_batch_host(self, topics: Sequence[str]) -> List[Set[Hashable]]:
        """Pure-host batch match (the device-failure fallback path)."""
        out: List[Set[Hashable]] = []
        for t in topics:
            with self._mlock:
                out.append(self.match_host(T.words(t)))
        return out

    def _overlay(
        self, topics, words, rows, gpos, ovf, snap
    ) -> List[Set[Hashable]]:
        _, _, fid_arr, delta, deep, deleted = snap
        fids_flat = fid_arr[gpos]
        per_row = np.bincount(rows, minlength=len(words))
        chunks = np.split(fids_flat, np.cumsum(per_row)[:-1])
        out: List[Set[Hashable]] = []
        for i, ws in enumerate(words):
            if ovf[i]:
                out.append(self.match_host(ws))
                continue
            fids: Set[Hashable] = set(chunks[i].tolist())
            if deleted:
                fids -= deleted
            if self._exact:
                fids |= self._exact.get(topics[i], set())
            if len(delta):
                fids |= delta.match_words(ws)
            if len(deep):
                fids |= deep.match_words(ws)
            out.append(fids)
        return out

    def match_batch_flat(self, words: Sequence[T.Words]):
        """Device fast path: encoded topics -> flat row-sorted
        ``(topic_row, position)`` pairs into the base snapshot plus a
        per-row overflow flag.  The device ships only the compact code
        form; fan-out expansion happens host-side with vectorized CSR
        (`expand_codes_host`) — the SURVEY §7 amplification strategy.
        Rows flagged ``ovf`` must be re-matched on the host.  Callers
        must still overlay exact/delta/deep/deleted state."""
        with self._mlock:
            snap = self._snapshot_refs()
        return self._flat_from_snapshot(snap, words)

    def _flat_from_snapshot(self, snap: Tuple, words: Sequence[T.Words]):
        from .ops.automaton import expand_codes_host
        from .ops.match_kernel import match_batch

        aut, tables = snap[0], snap[1]
        tokens, lengths, dollar = encode_topics(
            self._tdict, words, aut.kernel_levels
        )
        b = tokens.shape[0]
        tokens, lengths, dollar = _pad_batch(tokens, lengths, dollar)
        codes, _, ovf = match_batch(
            *tables,
            tokens,
            lengths,
            dollar,
            probes=aut.probes,
            f_width=self.f_width,
            m_cap=self.m_cap,
        )
        rows, pos = expand_codes_host(
            aut.code_off, aut.code_idx, np.asarray(codes)[:b]
        )
        return rows, pos, np.asarray(ovf)[:b]
