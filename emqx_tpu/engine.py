"""MatchEngine: the subscription-matching core, TPU-accelerated.

Mirrors the reference's v2 router split (/root/reference/apps/emqx/src/
emqx_router.erl:476-525): exact (non-wildcard) filters in an O(1) host
hash map (`?ROUTE_TAB` direct lookup), wildcard filters in an index —
here a device-resident array automaton batch-matched by
`ops.match_kernel`, not an ordered-set skip-scan.

Subscription churn vs XLA immutability (SURVEY §7 "hard parts") is
handled the way `emqx_router_syncer` batches route ops: mutations land
in a host-side *delta* trie immediately (correct from the next match on)
and are folded into a rebuilt device automaton once the delta passes a
threshold.  Deletions are masked out of stale device results by fid.

Any topic the kernel flags (frontier overflow, match-cap overflow, too
deep) is re-matched on the `HostTrie` oracle, so results are always
exact regardless of kernel capacity bounds.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import topic as T
from .ops.automaton import Automaton, build_automaton
from .ops.dictionary import TokenDict, encode_topics
from .ops.trie_host import HostTrie


class MatchEngine:
    """Mutable filter set with batched matching.

    ``use_device=None`` (default) auto-enables the JAX path when any
    wildcard filters exist; ``False`` forces pure-host matching (the
    reference-equivalent CPU path kept as fallback per BASELINE.json).
    """

    def __init__(
        self,
        max_levels: int = 16,
        f_width: int = 16,
        m_cap: int = 128,
        rebuild_threshold: int = 4096,
        use_device: Optional[bool] = None,
    ) -> None:
        self.max_levels = max_levels
        self.f_width = f_width
        self.m_cap = m_cap
        self.rebuild_threshold = rebuild_threshold
        self.use_device = use_device
        self._exact: Dict[str, Set[Hashable]] = {}
        self._wild = HostTrie()  # full wildcard set: fallback + rebuild source
        self._delta = HostTrie()  # wildcard filters added since last build
        self._deep = HostTrie()  # filters too deep for the device index
        self._by_fid: Dict[Hashable, str] = {}
        self._deleted: Set[Hashable] = set()  # deleted since last build
        self._tdict = TokenDict()
        self._aut: Optional[Automaton] = None
        self._dev: Optional[Tuple] = None  # device copies of table arrays
        self._base_fids: Set[Hashable] = set()

    # ------------------------------------------------------------- mutation

    def insert(self, flt: str, fid: Hashable) -> None:
        T.validate_filter(flt)
        if fid in self._by_fid:
            if self._by_fid[fid] == flt:
                return
            self.delete(fid)
        self._by_fid[fid] = flt
        if T.is_wildcard(flt):
            self._wild.insert(flt, fid)
            ws = T.words(flt)
            body_depth = len(ws) - (1 if ws[-1] == "#" else 0)
            if body_depth > self.max_levels:
                self._deep.insert(flt, fid)
            else:
                # Do NOT clear a tombstone here: if the fid previously
                # carried a *different* filter in the base snapshot, the
                # tombstone is what masks the stale device entry.  The
                # delta trie serves the re-inserted filter until rebuild.
                self._delta.insert(flt, fid)
                if len(self._delta) >= self.rebuild_threshold:
                    self.rebuild()
        else:
            self._exact.setdefault(flt, set()).add(fid)

    def delete(self, fid: Hashable) -> bool:
        flt = self._by_fid.pop(fid, None)
        if flt is None:
            return False
        if T.is_wildcard(flt):
            self._wild.delete_id(fid)
            self._delta.delete_id(fid)
            self._deep.delete_id(fid)
            if fid in self._base_fids:
                self._deleted.add(fid)
        else:
            ids = self._exact.get(flt)
            if ids is not None:
                ids.discard(fid)
                if not ids:
                    del self._exact[flt]
        return True

    def __len__(self) -> int:
        return len(self._by_fid)

    # -------------------------------------------------------------- rebuild

    def rebuild(self, hash_buckets: int = 0) -> None:
        """Fold the delta into a fresh device automaton snapshot."""
        filters = [
            (fid, ws)
            for fid, ws in self._wild.filters()
            if fid not in self._deep
        ]
        self._aut = build_automaton(
            filters, self._tdict, self.max_levels, hash_buckets=hash_buckets
        )
        self._base_fids = {fid for fid, _ in filters}
        self._delta = HostTrie()
        self._deleted = set()
        self._dev = None  # lazily device_put on first device match

    def _device_tables(self):
        if self._dev is None:
            import jax

            self._dev = tuple(
                jax.device_put(a) for a in self._aut.device_arrays()
            )
        return self._dev

    # -------------------------------------------------------------- match

    def match(self, topic: str) -> Set[Hashable]:
        return self.match_batch([topic])[0]

    def match_host(self, topic_words: T.Words) -> Set[Hashable]:
        """Pure-host exact match (oracle path)."""
        out = set(self._exact.get(T.join(topic_words), ()))
        out |= self._wild.match_words(topic_words)
        return out

    def match_batch(self, topics: Sequence[str]) -> List[Set[Hashable]]:
        words = [T.words(t) for t in topics]
        device_on = (
            self.use_device is not False
            and self._aut is not None
            and self._aut.n_nodes > 1
        )
        if not device_on:
            return [self.match_host(ws) for ws in words]

        tokens, lengths, dollar = encode_topics(
            self._tdict, words, self._aut.kernel_levels
        )
        codes, counts, ovf = self._match_device(tokens, lengths, dollar)
        aut = self._aut
        out: List[Set[Hashable]] = []
        for i, ws in enumerate(words):
            if ovf[i]:
                out.append(self.match_host(ws))
                continue
            fids: Set[Hashable] = set(self._exact.get(topics[i], ()))
            for code in codes[i, : counts[i]]:
                for pos in aut.expand(int(code)):
                    fid = aut.filters[pos][0]
                    if fid not in self._deleted:
                        fids.add(fid)
            fids |= self._delta.match_words(ws)
            fids |= self._deep.match_words(ws)
            out.append(fids)
        return out

    def _match_device(self, tokens, lengths, dollar):
        from .ops.match_kernel import match_batch

        # pad the batch to a power-of-two bucket so XLA sees a bounded
        # set of shapes (no recompile storm on ragged publish batches)
        b = tokens.shape[0]
        bp = 16
        while bp < b:
            bp *= 2
        if bp != b:
            pad = bp - b
            tokens = np.pad(tokens, ((0, pad), (0, 0)), constant_values=-4)
            lengths = np.pad(lengths, (0, pad))  # length 0 => inert row
            dollar = np.pad(dollar, (0, pad), constant_values=True)

        tables = self._device_tables()
        codes, counts, ovf = match_batch(
            *tables,
            tokens,
            lengths,
            dollar,
            probes=self._aut.probes,
            f_width=self.f_width,
            m_cap=self.m_cap,
        )
        return np.asarray(codes)[:b], np.asarray(counts)[:b], np.asarray(ovf)[:b]
