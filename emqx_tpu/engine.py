"""MatchEngine: the subscription-matching core, TPU-accelerated.

Mirrors the reference's v2 router split (/root/reference/apps/emqx/src/
emqx_router.erl:476-525): exact (non-wildcard) filters in an O(1) host
hash map (`?ROUTE_TAB` direct lookup), wildcard filters in an index —
here a device-resident array automaton batch-matched by
`ops.match_kernel`, not an ordered-set skip-scan.

Subscription churn vs XLA immutability (SURVEY §7 "hard parts") is
handled the way `emqx_router_syncer` batches route ops: mutations land
in a host-side *delta* trie immediately (correct from the next match on)
and are folded into a rebuilt device automaton once the delta passes a
threshold.  Deletions are masked out of stale device results by fid.

Any topic the kernel flags (frontier overflow, match-cap overflow, too
deep) is re-matched on the `HostTrie` oracle, so results are always
exact regardless of kernel capacity bounds.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import failpoints
from . import topic as T
from .tp import tp
from .ops.automaton import Automaton, build_automaton
from .ops.dictionary import SENTINEL, TokenDict, encode_topics
from .ops.trie_host import HostTrie
from .ops.trie_native import make_trie


def _pad_batch(tokens, lengths, dollar):
    """Pad the batch to a power-of-two bucket so XLA sees a bounded set
    of shapes (no recompile storm on ragged publish batches)."""
    b = tokens.shape[0]
    bp = 16
    while bp < b:
        bp *= 2
    if bp != b:
        pad = bp - b
        tokens = np.pad(tokens, ((0, pad), (0, 0)), constant_values=-4)
        lengths = np.pad(lengths, (0, pad))  # length 0 => inert row
        dollar = np.pad(dollar, (0, pad), constant_values=True)
    return tokens, lengths, dollar


def _pad_nodes_pow2(aut: Automaton, minimum: int = 16) -> None:
    """Pad the node table to a power-of-two capacity class: rebuild N ->
    N+delta then only crosses a traced-shape boundary when capacity
    doubles, so XLA reuses the compiled kernel instead of recompiling
    after every rebuild.  Padded rows are inert (no '+' child, no
    terminal flags) and unreachable (no edges point at them)."""
    n = aut.node_rows.shape[0]
    cap = minimum
    while cap < n:
        cap *= 2
    if cap != n:
        pad = np.zeros((cap - n, 8), np.int32)
        pad[:, 0] = int(SENTINEL)
        pad[:, 4] = -1  # no incoming edge: verification-dead
        pad[:, 5] = -1
        aut.node_rows = np.concatenate([aut.node_rows, pad])


def enable_compile_cache(path: str = "data/xla_cache") -> None:
    """Turn on JAX's persistent compilation cache.  A first-use XLA
    compile of a new automaton capacity class takes seconds and stalls
    concurrent matches on the backend; with the on-disk cache each
    shape class compiles once EVER (across restarts), so a production
    broker's rebuild ladder warms from disk in milliseconds.  Safe to
    call repeatedly."""
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        import logging

        logging.getLogger("emqx_tpu.engine").debug(
            "compilation cache unavailable", exc_info=True
        )


def _validate_filter(flt: str):
    """Fused split + validate + wildcard classification via C-speed
    string counts (a per-level Python loop was ~20% of the insert hot
    path): every '+'/'#' must be a WHOLE level — true iff its count in
    the string equals the count of levels that are exactly that
    character — and the single '#' must be the last level.  Returns
    ``(words, is_wildcard, n_hash)``; raises before any mutation."""
    ws = tuple(flt.split("/"))
    if (
        not flt
        or "\x00" in flt
        or len(flt) > 65535
        or (len(flt) > 16383 and len(flt.encode()) > 65535)
    ):
        raise ValueError(f"invalid topic filter: {flt!r}")
    n_hash = flt.count("#")
    n_plus = flt.count("+")
    wild = bool(n_hash or n_plus)
    if wild:
        if n_plus != ws.count("+"):
            raise ValueError(f"wildcard not a whole level: {flt!r}")
        if n_hash:
            if n_hash != 1 or ws[-1] != "#":
                raise ValueError(f"'#' not a whole last level: {flt!r}")
    return ws, wild, n_hash


def make_fid_arr(fids: List[Hashable]) -> np.ndarray:
    """Position -> fid, vectorized-indexable: int64 fast path when every
    fid is an int; object fallback (filled by assignment so tuple fids
    stay 1-D, not broadcast)."""
    if fids and all(type(f) is int for f in fids):
        return np.array(fids, np.int64)
    arr = np.empty(len(fids), object)
    arr[:] = fids
    return arr


class _EncArena:
    """Append-only encode arena: the incremental build cache.

    Row arrays (token matrix, body length, hash flag, fid) grow by
    doubling; a deleted or superseded filter's row is DEAD-MARKED
    (``blen = -1`` — ``blen == 0`` is a LIVE bare-'#' filter;
    `assemble_automaton` keeps rows with ``blen >= 0``) instead of
    compacted, so applying a delta is O(delta) with NO full-array
    copies (the previous keep-mask + ``np.concatenate`` scheme copied
    ~64 MB per rebuild at 1M filters while holding the GIL — a 40-50 ms
    publish-visible stall under churn).  Row positions are stable for
    the arena's lifetime, so a live automaton's ``code_idx``/``fid``
    views stay valid while later generations append.

    Single-writer: all mutation happens in whichever builder thread
    holds the engine's ``_enc_lock``; matching never touches the arena.
    """

    __slots__ = ("max_levels", "mat", "blen", "ish", "flist", "fids",
                 "rows", "dead")

    def __init__(self, max_levels: int, cap: int = 1024) -> None:
        from .ops.dictionary import PAD_TOK

        self.max_levels = max_levels
        self.mat = np.full((cap, max_levels), PAD_TOK, np.int32)
        self.blen = np.zeros(cap, np.int32)
        self.ish = np.zeros(cap, bool)
        self.flist: List[Tuple[Hashable, Tuple[str, ...]]] = []
        self.fids = np.zeros(cap, np.int64)
        self.rows: Dict[Hashable, int] = {}  # live fid -> row
        self.dead = 0

    @property
    def used(self) -> int:
        return len(self.flist)

    def _grow(self, need: int) -> None:
        from .ops.dictionary import PAD_TOK

        cap = len(self.blen)
        while cap < need:
            cap *= 2
        if cap == len(self.blen):
            return
        mat = np.full((cap, self.max_levels), PAD_TOK, np.int32)
        # chunked copy with yields: one big memcpy holds the GIL
        step = 1 << 16
        for i in range(0, self.used, step):
            j = min(i + step, self.used)  # dest is LARGER: clip both
            mat[i:j] = self.mat[i:j]
            time.sleep(0)
        self.mat = mat
        self.blen = np.resize(self.blen, cap)
        self.ish = np.resize(self.ish, cap)
        if self.fids.dtype == object:
            f2 = np.empty(cap, object)
            f2[: self.used] = self.fids[: self.used]
            self.fids = f2
        else:
            self.fids = np.resize(self.fids, cap)

    def _set_fid(self, row: int, fid: Hashable) -> None:
        if self.fids.dtype != object and type(fid) is not int:
            obj = np.empty(len(self.fids), object)
            obj[: self.used] = self.fids[: self.used].tolist()
            self.fids = obj
        self.fids[row] = fid

    def apply(self, items, dropped_fids, tdict) -> None:
        """Dead-mark ``dropped_fids`` and rows superseded by ``items``,
        then encode+append ``items``.  Yields the GIL every few
        thousand rows — this runs in a background builder thread and a
        long pure-Python burst would stall the insert/publish thread."""
        from .ops.dictionary import encode_filter

        for fid in dropped_fids:
            r = self.rows.pop(fid, None)
            if r is not None:
                self.blen[r] = -1  # dead marker (0 = live bare '#')
                self.dead += 1
        self._grow(self.used + len(items))
        u0 = self.used
        n_items = len(items)
        batch = n_items >= 64 and tdict.encode_filters_into(
            items, self.max_levels,
            self.mat[u0:u0 + n_items], self.blen[u0:u0 + n_items],
            self.ish[u0:u0 + n_items],
        )
        n = 0
        for fid, ws in items:
            r = self.rows.get(fid)
            if r is not None:  # re-insert supersedes the old row
                self.blen[r] = -1
                self.dead += 1
            row = u0 + n if batch else self.used
            if not batch:
                body, hsh = encode_filter(tdict, ws)
                if len(body) > self.max_levels:
                    raise ValueError(
                        f"filter deeper than max_levels="
                        f"{self.max_levels}: {ws}"
                    )
                if row >= len(self.blen):
                    self._grow(row + 1)
                self.mat[row, : len(body)] = body
                self.blen[row] = len(body)
                self.ish[row] = hsh
            self._set_fid(row, fid)
            self.flist.append((fid, ws))
            self.rows[fid] = row
            n += 1
            if n % 1024 == 0:
                time.sleep(0)  # let the insert thread breathe
        if self.dead > max(self.used // 2, 4096):
            self._compact(tdict)

    def _compact(self, tdict) -> None:
        """Occasional dead-row sweep (amortized by the 50% trigger):
        rebuilds the arena from its live rows so sustained
        insert+delete churn cannot grow it without bound."""
        live = sorted(self.rows.items(), key=lambda kv: kv[1])
        fresh = _EncArena(self.max_levels, cap=max(len(live) * 2, 1024))
        items = [(fid, self.flist[r][1]) for fid, r in live]
        fresh.apply(items, (), tdict)
        for name in ("mat", "blen", "ish", "flist", "fids", "rows"):
            setattr(self, name, getattr(fresh, name))
        self.dead = 0

    def views(self):
        """(mat, blen, ish, flist) views for `assemble_automaton` —
        zero-copy; positions align with `fid_view`."""
        u = self.used
        return self.mat[:u], self.blen[:u], self.ish[:u], self.flist

    def fid_view(self) -> np.ndarray:
        """Stable position->fid array for the CURRENT used span (valid
        even as later generations append, until a capacity doubling
        replaces the buffer — which leaves this view's buffer intact)."""
        return self.fids[: self.used]


class _ResidualView:
    """Read view of "wildcard filters inserted after the fold
    watermark", backed by the seq-tagged `_wild` trie — the overlay's
    stand-in for the residual trie that no longer exists.  `__len__`
    is the skip-check and must never under-count for THIS view's
    watermark (a fold adopting mid-batch moves the engine's live
    counter down, but entries between this snapshot's watermark and
    the new one are only covered by the NEW automaton, not the
    snapshot's) — so it reports the seq-span upper bound, which only
    inserts advance."""

    __slots__ = ("_wild", "_min_seq")

    def __init__(self, wild, watermark: int) -> None:
        self._wild = wild
        self._min_seq = watermark + 1

    def __len__(self) -> int:
        return max(self._wild.last_seq() - self._min_seq + 1, 0)

    def match_words(self, ws) -> Set[Hashable]:
        return self._wild.match_since_words(ws, self._min_seq)


class MatchEngine:
    """Mutable filter set with batched matching.

    ``use_device=None`` (default) auto-enables the JAX path when any
    wildcard filters exist; ``False`` forces pure-host matching (the
    reference-equivalent CPU path kept as fallback per BASELINE.json).
    """

    def __init__(
        self,
        max_levels: int = 16,
        f_width: int = 8,
        m_cap: int = 128,
        rebuild_threshold: int = 4096,
        use_device: Optional[bool] = None,
        background_rebuild: bool = False,
        delta_aut_threshold: int = 1024,
        delta_fold_factor: int = 2,
    ) -> None:
        self.max_levels = max_levels
        self.f_width = f_width
        self.m_cap = m_cap
        self.rebuild_threshold = rebuild_threshold
        self.use_device = use_device
        self.background_rebuild = background_rebuild
        # wired by the broker's overload ladder (olp L1): a truthy
        # return defers scheduling a background rebuild — the delta
        # tiers keep serving correctness, and the first post-recovery
        # mutation past the threshold triggers it.  Must be cheap and
        # non-raising; may be called with engine locks held.
        self.defer_rebuild = None
        self.delta_aut_threshold = delta_aut_threshold
        # fold when the residual reaches delta/factor: a smaller factor
        # folds less often (less background assemble stealing the GIL
        # from the insert thread), at the cost of a larger host-matched
        # residual between folds — profiled best at 2 for sustained
        # 100k-scale churn
        self.delta_fold_factor = delta_fold_factor
        self._exact: Dict[str, Set[Hashable]] = {}
        self._wild = make_trie()  # full wildcard set: fallback + rebuild source
        # wildcard filters added since last build: fid -> words.  A
        # plain dict (0.2 us insert), because matching against the delta
        # always goes through either the folded delta automaton or the
        # watermark residual view on _wild — never this map directly.
        self._delta: Dict[Hashable, Tuple[str, ...]] = {}
        self._deep = make_trie()  # filters too deep for the device index
        self._by_fid: Dict[Hashable, str] = {}
        # per-generation tombstones: a delete masks the fid only in the
        # snapshot(s) that still carry its stale entry.  Folds/rebuilds
        # REPLACE these sets (never mutate in place) so an in-flight
        # match's captured snapshot stays internally consistent.
        self._deleted_base: Set[Hashable] = set()
        self._deleted_daut: Set[Hashable] = set()
        self._tdict = TokenDict()
        self._aut: Optional[Automaton] = None
        self._dev: Optional[Tuple] = None  # device copies of table arrays
        self._n_base = 0  # live filters in the base snapshot
        # encode arena of the base builds: in-place incremental
        # re-encode of only the delta (`_EncArena`)
        self._build_cache: Optional[_EncArena] = None
        # device-resident DELTA automaton (VERDICT r3 task: the churn
        # fix).  The host delta overlay is O(delta) per topic — the
        # scaling cliff during a long base rebuild.  Instead the delta
        # folds into a SECOND, small automaton matched on-device next to
        # the base; only the residual since its last build stays
        # host-matched.  Rebuild cadence is geometric
        # (max(threshold, |delta|/4)) so build work amortizes O(1) per
        # insert, and tables pad to power-of-two capacity classes so
        # XLA re-uses a bounded set of compiled shapes instead of
        # recompiling per build.
        self._daut: Optional[Automaton] = None
        self._ddev: Optional[Tuple] = None
        self._dfid_arr: Optional[np.ndarray] = None
        self._daut_fids: Set[Hashable] = set()
        self._fold_cache: Optional[_EncArena] = None  # fold encode arena
        # STICKY fold capacity classes: each new (node, bucket) shape
        # costs an executable load on the backend (~1.5 s through the
        # tunnel) that stalls concurrent matches; never shrinking the
        # ladder across rebuilds means each class loads once per
        # process instead of once per rebuild cycle
        self._fold_min_nodes = 4096
        self._fold_min_buckets = 2048
        # The residual ("delta since the last fold") is NOT a second
        # trie: `_wild` tags every insert with a monotonically
        # increasing sequence number, and the residual is simply the
        # view "seq > _fold_watermark" (`match_since_words`).  A fold
        # then costs one watermark bump instead of a residual-trie
        # rebuild, and each insert pays ONE native trie insert, not two.
        self._fold_watermark = 0
        self._residual_count = 0
        # append-only (fid, seq) log of inserts past the watermark; the
        # fold work-list derives from it in O(residual), and adopt
        # prunes it to the entries past the new watermark
        self._residual_log: List[Tuple[Hashable, int]] = []
        self._delta_seq: Dict[Hashable, int] = {}  # fid -> latest seq
        # async fold state: the assemble runs OFF the insert thread
        # (VERDICT r2 weak #4: a synchronous fold added ~170 ms stalls
        # to the insert path at 100k-delta scale).  `_fold_gen` guards
        # adoption — any base swap/rebuild bumps it, discarding an
        # in-flight fold whose inputs predate the new base.
        self._folding = False
        self._fold_async = True  # tests pin False for strict bounds
        self._fold_gen = 0
        self._fold_thread: Optional[threading.Thread] = None
        self._fold_deletes: Set[Hashable] = set()
        # background (double-buffered) rebuild state: the builder thread
        # assembles a new snapshot while matching continues on the live
        # one — the `emqx_router_syncer` no-stop-the-world property
        # (/root/reference/apps/emqx/src/emqx_router_syncer.erl:58)
        self._lock = threading.Lock()
        # serializes host-side mutation vs. the overlay/encode phases of
        # a match running on another thread (the PublishBatcher runs the
        # device step in an executor so the event loop keeps reading
        # sockets); the kernel call itself runs OUTSIDE this lock on an
        # immutable snapshot, so a SUBSCRIBE never waits on the device
        self._mlock = threading.RLock()
        # levels -> [ws->row-index dict, token matrix, lengths,
        # dollar, rows-used] (see _encode_rows)
        self._enc_cache: Dict[int, list] = {}
        # guards the encode cache: _encode_rows runs OUTSIDE _mlock
        # (the device step is deliberately lock-free), so two
        # concurrent match batches must not interleave row assignment
        self._enc_mutex = threading.Lock()
        self._enc_gen = 0
        # serializes TokenDict-mutating encodes (fold thread vs rebuild
        # snapshot): two concurrent encode_filters would interleave
        # TokenDict.add's check-then-act and could alias token ids
        self._enc_lock = threading.Lock()
        self._building = False
        self._rebuild_snap_seq = 0  # wild seq at the build snapshot
        self._built: Optional[Tuple] = None  # (aut, dev, fid_arr, base_fids)
        self._build_thread: Optional[threading.Thread] = None
        self._pending_inserts: List[Tuple[str, Hashable]] = []
        self._pending_deletes: Set[Hashable] = set()
        # ---- adaptive path policy (use_device=None, "auto") ----
        # The deployed broker must never be SLOWER with the device on
        # (VERDICT r4 weak #1): auto picks per window from measured
        # costs.  Latency mode (queue shallow) compares wall times —
        # over a high-RTT link (axon tunnel ~100 ms) small windows match
        # on the host trie in microseconds; co-located, the crossover
        # drops to a few hundred topics.  Throughput mode (congested)
        # compares HOST-SIDE CPU only: pipelining hides the device
        # round-trip, so offloading the match frees the one resource a
        # saturated single-core broker is starved of.
        self._host_us: Optional[float] = None   # host µs/topic EWMA
        self._dev_cpu_us: Optional[float] = None  # device-path host CPU
        self._dev_window_s: Optional[float] = None  # device window wall
        self._auto_stats = {"host_windows": 0, "dev_windows": 0,
                            "probes": 0}
        self._auto_seq = 0
        self._warmup_force = False
        # out-of-band device probing: when the policy is choosing host,
        # a one-shot background thread re-measures the device path
        # every ~10 s over a sample of RECENT REAL topics — never as
        # head-of-line latency in the live window stream (an in-band
        # probe window delays the ordered dispatch of everything
        # behind it by a full device round-trip)
        self._probe_topics: List[str] = []
        # first refresh waits a full interval: warmup() seeds the
        # estimates at boot, and an immediate probe lands exactly in
        # the first traffic burst (measured: one background probe ate
        # ~40% of a 1.5s flood on a single-core host)
        self._probe_last = time.monotonic()
        self._probe_running = False
        # compact-transfer capacity multiplier (x unique topics in the
        # window); doubles whenever the buffer clips, never shrinks
        self._ccap_mult = 2
        # (nodes, buckets, levels) classes already shape-warmed
        self._warmed_shapes: Set[Tuple[int, int, int]] = set()
        # ---- window decide step (dispatch decision columns) --------
        # The dispatch half's per-delivery decisions compute as one
        # vectorized pass (ops.match_kernel.decide_batch + its numpy
        # twin); host-vs-device resolves per window from per-delivery
        # cost EWMAs the same way `_auto_choose` does for matching,
        # and device faults feed the SAME circuit breaker, so 100%
        # device failure degrades both steps to host together.
        self.decide_force: Optional[str] = None  # "host"/"dev" pin (tests)
        self._dec_host_us: Optional[float] = None  # µs/delivery EWMAs
        self._dec_dev_us: Optional[float] = None
        self._dec_stats = {"host_windows": 0, "dev_windows": 0,
                           "dev_errors": 0}
        self._dec_cols_cache: Optional[Tuple] = None  # (rev, dev arrays)
        # EWMA hygiene: the FIRST device decide window pays the JIT
        # compile and must not poison the cost estimate, and a rare
        # in-band re-probe keeps it fresh while host is winning (the
        # step is micro-scale, so no out-of-band probe thread is
        # warranted the way matching's is)
        self._dec_dev_warm = False
        self._dec_seq = 0
        self._dec_probe_seq = 0
        # ---- rules x window matrix step (rule-engine predicates) ---
        # The rule engine's stacked WHERE programs (rules/predicate.py
        # StackedRules) evaluate over the window's shared column
        # planes as one rules x window boolean matrix
        # (ops.match_kernel.rules_eval_host / rules_eval_batch).
        # Host-vs-device resolves per window from per-CELL (rule x
        # message) cost EWMAs, device faults feed the SAME PR 1
        # breaker, and the device path additionally gates on f32
        # safety (the kernel computes in float32; arith programs and
        # f32-lossy columns stay on the float64 host twin).
        self.rules_force: Optional[str] = None  # "host"/"dev" pin
        self._rul_host_us: Optional[float] = None  # µs/cell EWMAs
        self._rul_dev_us: Optional[float] = None
        self._rul_stats = {"host_windows": 0, "dev_windows": 0,
                           "dev_errors": 0}
        self._rul_prog_cache: Optional[Tuple] = None  # (rev, arrays)
        self._rul_dev_warm = False
        self._rul_seq = 0
        self._rul_probe_seq = 0
        # ---- device-path circuit breaker (failure-driven degradation)
        # The auto policy above switches paths on measured COST; the
        # breaker switches on FAILURE: `breaker_threshold` consecutive
        # device-step exceptions (XLA compile/OOM, tunnel loss) — or a
        # window exceeding `breaker_deadline` seconds of wall, the
        # watchdog — trip matching to host-only.  A background probe
        # re-tries the device every `breaker_probe_interval` seconds
        # and re-closes the breaker on success.  The broker wires the
        # trip/clear callbacks into its AlarmRegistry ($SYS alarm) and
        # metrics.
        self.breaker_threshold = 3
        self.breaker_probe_interval = 5.0
        self.breaker_deadline: Optional[float] = 30.0
        self.on_breaker_trip = None  # callable(info_dict)
        self.on_breaker_clear = None  # callable(info_dict)
        self._brk_failures = 0  # consecutive device-step failures
        self._brk_open = False
        self._brk_opened_at = 0.0
        self._brk_probe_last = 0.0
        self._brk_probing = False
        self._brk_stats = {"trips": 0, "device_errors": 0,
                           "slow_windows": 0, "probes": 0}
        # observability.Profiler installed by the broker: lifecycle
        # events (XLA shape compiles, device_put transfer bytes, delta
        # folds, rebuilds) + the tokenize stage histogram.  None =
        # zero-cost no-op (standalone engines, benches)
        self.profiler = None

    # ------------------------------------------------------------- mutation

    def insert(self, flt: str, fid: Hashable) -> None:
        with self._mlock:
            # _mlock IS the mutation/snapshot serialization for the
            # native token matrix the call mutates with the GIL
            # released — holding it across the native span is the
            # design, not an accident
            # brokerlint: ignore[LOCK402]
            self._insert_locked(flt, fid)

    def insert_many(self, pairs: Sequence[Tuple[str, Hashable]]) -> None:
        """Windowed batch insert — the `emqx_router_syncer` shape
        (route ops land in batches of up to ?MAX_BATCH_SIZE,
        /root/reference/apps/emqx/src/emqx_router_syncer.erl:58): one
        lock acquisition and ONE GIL-released native trie call cover
        the whole window's fresh wildcard entries, with replacements /
        exact / deep filters peeling off to the single-item path.
        Validation still runs per item BEFORE any mutation."""
        # last-wins within the window (same as per-item insert): a fid
        # listed twice must not have its FIRST filter batch-inserted
        # after the second took the replacement path
        if len({fid for _, fid in pairs}) != len(pairs):
            dedup: Dict[Hashable, str] = {}
            for flt, fid in pairs:
                dedup[fid] = flt
            pairs = [(flt, fid) for fid, flt in dedup.items()]
        # validate the WHOLE window before any mutation: a bad filter
        # mid-batch must not leave earlier entries half-applied
        parsed = [
            (flt, fid, *_validate_filter(flt)) for flt, fid in pairs
        ]
        with self._mlock:
            if self._built is not None:
                self._poll_swap()
            batch: List[Tuple[str, Hashable, Tuple[str, ...]]] = []
            for flt, fid, ws, wild, n_hash in parsed:
                prev = self._by_fid.get(fid)
                if prev is not None:
                    if prev == flt:
                        continue
                    # same _mlock-serializes-the-native-matrix design
                    # as `insert` # brokerlint: ignore[LOCK402]
                    self._insert_locked(flt, fid)
                    continue
                if not wild:
                    self._by_fid[fid] = flt
                    self._exact.setdefault(flt, set()).add(fid)
                    continue
                if len(ws) - (1 if n_hash else 0) > self.max_levels:
                    # same _mlock design # brokerlint: ignore[LOCK402]
                    self._insert_locked(flt, fid)
                    continue
                self._by_fid[fid] = flt
                batch.append((flt, fid, ws))
            if not batch:
                return
            seqs = self._wild.insert_batch(batch)
            delta = self._delta
            dseq = self._delta_seq
            log = self._residual_log
            fresh = 0
            for (flt, fid, ws), seq in zip(batch, seqs):
                delta[fid] = ws
                if seq:
                    dseq[fid] = seq
                    log.append((fid, seq))
                    fresh += 1
            self._residual_count += fresh
            if self._building:
                self._pending_inserts.extend(
                    (flt, fid) for flt, fid, _ in batch
                )
            if len(delta) >= self.rebuild_threshold:
                if self.background_rebuild:
                    if self.defer_rebuild is None or \
                            not self.defer_rebuild():
                        self._start_background_rebuild()
                else:
                    # synchronous rebuild variant keeps _mlock across
                    # the native sort on purpose: mutations must not
                    # interleave with the table swap
                    # brokerlint: ignore[LOCK402]
                    self.rebuild()
            if self.use_device is not False and (
                self._residual_count
                >= max(self.delta_aut_threshold,
                       len(self._delta) // self.delta_fold_factor)
            ):
                self._fold_delta_aut()

    def _insert_locked(self, flt: str, fid: Hashable) -> None:
        if self._built is not None:
            self._poll_swap()
        prev = self._by_fid.get(fid)
        if prev is not None and prev == flt:
            return
        # engine-level filters are REAL topics ($share is stripped by
        # the router before it gets here); validation runs BEFORE any
        # mutation so a rejected insert cannot destroy the fid's
        # existing subscription
        ws, wild, n_hash = _validate_filter(flt)
        if prev is not None:
            self._delete_locked(fid)
        self._by_fid[fid] = flt
        if wild:
            seq = self._wild.insert(flt, fid, ws=ws)
            body_depth = len(ws) - (1 if n_hash else 0)
            if body_depth > self.max_levels:
                self._deep.insert(flt, fid, ws=ws)
            else:
                # Do NOT clear a tombstone here: if the fid previously
                # carried a *different* filter in the base snapshot, the
                # tombstone is what masks the stale device entry.  The
                # residual view serves the re-inserted filter until
                # rebuild (its seq is past the watermark, and set-union
                # across tiers dedups any daut/residual double-serve).
                self._delta[fid] = ws
                if seq:
                    self._delta_seq[fid] = seq
                    log = self._residual_log
                    log.append((fid, seq))
                    self._residual_count += 1
                    if len(log) > 1024 and len(log) > 4 * max(
                        self._residual_count, 1
                    ):
                        # amortized compaction: churn that never crosses
                        # the fold threshold (or runs with the device
                        # off) must not grow the log without bound
                        wm = self._fold_watermark
                        dseq = self._delta_seq
                        self._residual_log = [
                            e for e in log
                            if e[1] > wm and dseq.get(e[0]) == e[1]
                        ]
                if self._building:
                    self._pending_inserts.append((flt, fid))
                if len(self._delta) >= self.rebuild_threshold:
                    if self.background_rebuild:
                        if self.defer_rebuild is None or \
                                not self.defer_rebuild():
                            self._start_background_rebuild()
                    else:
                        self.rebuild()
                if self.use_device is not False and (
                    self._residual_count
                    >= max(self.delta_aut_threshold,
                           len(self._delta) // self.delta_fold_factor)
                ):
                    self._fold_delta_aut()
        else:
            self._exact.setdefault(flt, set()).add(fid)

    def delete(self, fid: Hashable) -> bool:
        with self._mlock:
            return self._delete_locked(fid)

    def _delete_locked(self, fid: Hashable) -> bool:
        flt = self._by_fid.pop(fid, None)
        if flt is None:
            return False
        if T.is_wildcard(flt):
            self._wild.delete_id(fid)
            self._delta.pop(fid, None)
            seq = self._delta_seq.pop(fid, None)
            if seq is not None and seq > self._fold_watermark:
                self._residual_count -= 1
            self._deep.delete_id(fid)
            # unconditional tombstones: membership checks against the
            # base/daut fid sets would race the builder threads'
            # in-place arena mutation; masking a fid no snapshot
            # carries is harmless (set subtraction of an absent
            # element), and both sets reset at the next build anyway
            self._deleted_base.add(fid)
            self._deleted_daut.add(fid)
            if self._folding:
                self._fold_deletes.add(fid)
            if self._building:
                self._pending_deletes.add(fid)
        else:
            ids = self._exact.get(flt)
            if ids is not None:
                ids.discard(fid)
                if not ids:
                    del self._exact[flt]
        return True

    def __len__(self) -> int:
        return len(self._by_fid)

    # -------------------------------------------------------------- rebuild

    def _snapshot_filters(self) -> List[Tuple[Hashable, T.Words]]:
        return [
            (fid, ws)
            for fid, ws in self._wild.filters()
            if fid not in self._deep
        ]

    def _snapshot_inputs(self):
        """Cheap coherent capture of the build work-list; the O(delta)
        encode itself runs in `_build` (i.e. in the BUILDER thread for
        background rebuilds — encoding 65k filters on the insert thread
        at the threshold crossing was a ~150 ms publish-visible
        stall)."""
        if self._build_cache is None:
            return ("full", self._snapshot_filters())
        return (
            "delta",
            list(self._delta.items()),
            set(self._deleted_base),
        )

    def _build(
        self, inputs, hash_buckets: int = 0, device_put: bool = False
    ):
        from .ops.automaton import assemble_automaton

        with self._enc_lock:
            kind = inputs[0]
            if kind == "full":
                arena = _EncArena(self.max_levels)
                arena.apply(inputs[1], (), self._tdict)
            else:
                arena = self._build_cache
                arena.apply(inputs[1], inputs[2], self._tdict)
            mat, blen, ish, flist = arena.views()
            fid_arr = arena.fid_view()
            n_live = len(arena.rows)
        aut = assemble_automaton(
            mat,
            blen,
            ish,
            flist,
            max_levels=self.max_levels,
            hash_buckets=hash_buckets,
        )
        _pad_nodes_pow2(aut)  # stable kernel shapes across rebuilds
        dev = None
        if device_put:
            dev = self._device_put(aut)
        return aut, dev, fid_arr, n_live, arena

    def _device_put(self, aut, chunk_bytes: int = 1 << 17,
                    throttle: bool = True):
        """Upload the automaton tables, big ones in chunks concatenated
        ON DEVICE: one monolithic transfer of a 10M-sub table (~100 MB)
        monopolizes the host->device link for seconds, queueing the
        live match path's small batches behind it.  Chunking alone is
        not enough — dispatching all chunks back-to-back still fills
        the link FIFO ahead of any match — so a short SLEEP between
        chunks leaves a gap where a concurrently-submitted match's
        input lands between chunk i and i+1 and waits one chunk time
        (~13 ms on the ~10 MB/s axon tunnel) instead of the whole
        upload (churn p99 stalls, VERDICT r4 #4).  Uploads run on the
        background fold/build threads, so the sleeps cost nothing on
        the match or insert paths."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        total_bytes = 0
        out = []
        for a in aut.device_arrays():
            if isinstance(a, np.ndarray):
                total_bytes += a.nbytes
            if (
                not isinstance(a, np.ndarray)
                or a.nbytes <= 2 * chunk_bytes
            ):
                out.append(jax.device_put(a))
                continue
            rows_per = max(chunk_bytes // max(a.strides[0], 1), 1)
            parts = []
            for i in range(0, len(a), rows_per):
                parts.append(jax.device_put(a[i:i + rows_per]))
                if throttle:
                    # throttled uploads only run on the background
                    # fold/build threads; the loop-reachable
                    # _device_tables path passes throttle=False, so
                    # this sleep never parks the event loop
                    # brokerlint: ignore[ASYNC101]
                    time.sleep(0.002)
            out.append(jnp.concatenate(parts, axis=0))
        prof = self.profiler
        if prof is not None:
            prof.event(
                "device_put", time.perf_counter() - t0,
                bytes=total_bytes, throttled=throttle,
            )
        return tuple(out)

    def _fold_delta_aut(self) -> None:
        """Fold the whole current delta into the second automaton
        (geometric cadence keeps this O(1) amortized per insert).  Node
        rows pad to a power-of-two capacity class (min 4096) and the
        hash table to a minimum bucket count, so successive folds reuse
        compiled kernel shapes; the scan length is pinned likewise.

        Two-phase, called under ``_mlock``: only the O(residual)
        work-list capture runs inline; the encode, assemble, upload and
        shape warm all run in a daemon thread, and the result is
        adopted only if no base swap happened meanwhile (``_fold_gen``).
        Matching keeps using the old delta automaton + the live
        residual view (`match_since_words` past the old watermark)
        until the swap, so nothing stalls and nothing is missed; the
        swap itself is a watermark bump, not a residual rebuild."""
        from .ops.automaton import assemble_automaton

        if self._folding:
            return
        # under _mlock: capture the work-list only (no encoding here —
        # the O(residual) encode runs in the fold thread too, off the
        # insert path).  The log dedups in place: an entry is live iff
        # it still carries its fid's latest seq.
        live = [
            (fid, seq)
            for fid, seq in self._residual_log
            if self._delta_seq.get(fid) == seq
        ]
        self._residual_log = live
        new_items = [(fid, self._delta[fid]) for fid, _ in live]
        cache = self._fold_cache
        if cache is None:
            full_items = list(self._delta.items())
            if not full_items:
                return
        else:
            if not new_items and not self._deleted_daut:
                return
            full_items = None
        deleted_snap = set(self._deleted_daut)
        snap_seq = self._wild.last_seq()
        gen = self._fold_gen
        # fire BEFORE flipping _folding: a tp-harness exception here
        # (injection / ordering timeout) must not wedge folds off
        tp("fold_capture", gen=gen, snap_seq=snap_seq,
           n_new=len(new_items))
        self._folding = True
        self._fold_deletes = set()

        def work():
            aut = None
            t_fold = time.perf_counter()
            try:
                with self._enc_lock:
                    if cache is None:
                        arena = _EncArena(self.max_levels)
                        arena.apply(full_items, (), self._tdict)
                    else:
                        arena = cache
                        arena.apply(new_items, deleted_snap, self._tdict)
                    inputs = arena.views()
                    fid_view = arena.fid_view()
                    live_fids = set(arena.rows)
                if not live_fids:  # everything deleted since snapshot
                    with self._mlock:
                        self._folding = False
                    return
                aut = assemble_automaton(
                    *inputs, max_levels=self.max_levels,
                    hash_buckets=self._fold_min_buckets,
                )
                _pad_nodes_pow2(aut, minimum=self._fold_min_nodes)
                aut.kernel_levels = self.max_levels + 1
                self._fold_min_nodes = aut.node_rows.shape[0]
                self._fold_min_buckets = len(aut.fp_rows)
                dev = None
                if self.use_device is not False:
                    try:
                        dev = self._device_put(aut)
                    except Exception:
                        dev = None
                    if dev is not None:
                        try:
                            # warm BEFORE the commit: a fold crossing
                            # a capacity class used to compile on the
                            # first post-commit match — a multi-second
                            # p99 stall ON the publish path.  A warm
                            # failure is non-fatal: the uploaded
                            # tables still serve (worst case the first
                            # match pays the compile).
                            self._warm_built(aut, dev)
                        except Exception:
                            import logging

                            logging.getLogger(
                                "emqx_tpu.engine"
                            ).debug("delta shape warm failed",
                                    exc_info=True)
                tp("fold_assemble_done", gen=gen)  # fault-inject point
            except Exception:
                import logging

                logging.getLogger("emqx_tpu.engine").exception(
                    "delta fold failed (%d filters); matching continues "
                    "on the residual overlay", len(new_items)
                )
                with self._mlock:
                    self._folding = False
                return
            # blocking tracepoint OUTSIDE the lock: force_ordering may
            # pin the adoption here while a match holds/needs _mlock.
            # A harness exception (ordering timeout) must release
            # _folding or no fold would ever run again.
            try:
                tp("fold_adopt", gen=gen)
            except BaseException:
                with self._mlock:
                    self._folding = False
                raise
            with self._mlock:
                self._folding = False
                if self._fold_gen != gen:
                    tp("fold_discard", gen=gen)
                    return  # base swapped underneath: fold is stale
                tp("fold_commit", gen=gen, watermark=snap_seq)
                self._fold_cache = arena
                self._daut = aut
                self._ddev = dev
                self._dfid_arr = fid_view
                self._daut_fids = live_fids
                # tombstones for fids deleted while the fold assembled
                # (fresh set: an in-flight match's captured snapshot
                # keeps the old set + old automaton pair); a fid
                # re-inserted during the fold stays tombstoned here but
                # its new seq is past the watermark, so the residual
                # view serves it — set union across tiers dedups
                self._deleted_daut = {
                    f for f in self._fold_deletes if f in self._daut_fids
                }
                self._fold_deletes = set()
                # the fold swap IS the watermark bump: entries at or
                # below snap_seq are covered by the new automaton
                self._fold_watermark = snap_seq
                self._residual_log = [
                    (fid, seq)
                    for fid, seq in self._residual_log
                    if seq > snap_seq
                ]
                self._residual_count = sum(
                    1
                    for fid, seq in self._residual_log
                    if self._delta_seq.get(fid) == seq
                )
            prof = self.profiler
            if prof is not None:
                prof.event(
                    "delta_fold", time.perf_counter() - t_fold,
                    n_new=len(new_items),
                )

        if self._fold_async:
            self._fold_thread = threading.Thread(
                target=work, name="matchengine-fold", daemon=True
            )
            self._fold_thread.start()
        else:
            work()  # _mlock is reentrant: safe from _insert_locked

    def _warm_built(self, aut, dev) -> None:
        """Compile the kernel for a freshly built automaton's table
        shapes (called off the hot path so the first real match never
        pays a shape-class compile in its own latency).  Sharded
        subclasses override — their tables feed a different kernel.

        Skips shape classes already warmed this process: the sticky
        fold capacity ladder means successive folds reuse one class,
        and each redundant warm queued two device round-trips that
        live matches had to wait behind (churn p99)."""
        from .ops.match_kernel import match_batch, match_batch_compact

        sig = (
            aut.node_rows.shape[0], len(aut.fp_rows), aut.kernel_levels
        )
        if sig in self._warmed_shapes:
            return
        self._warmed_shapes.add(sig)
        t0 = time.perf_counter()
        tokens = np.full((16, aut.kernel_levels), -4, np.int32)
        lengths = np.zeros(16, np.int32)
        dollar = np.zeros(16, bool)
        out = match_batch_compact(
            *dev, tokens, lengths, dollar,
            f_width=self.f_width, m_cap=self.m_cap, c_cap=32,
        )
        out[0].block_until_ready()
        # the DENSE kernel is the compact-clip fallback: warm it too,
        # or the first over-fanin window would pay its compile inside
        # the live match path
        out = match_batch(
            *dev, tokens, lengths, dollar,
            f_width=self.f_width, m_cap=self.m_cap,
        )
        out[0].block_until_ready()
        prof = self.profiler
        if prof is not None:
            prof.event(
                "xla_compile", time.perf_counter() - t0,
                nodes=sig[0], buckets=sig[1], levels=sig[2],
            )

    def _drop_delta_aut(self) -> None:
        self._daut = None
        self._ddev = None
        self._dfid_arr = None
        self._daut_fids = set()
        self._fold_cache = None
        # discard any in-flight fold: its inputs predate this state
        self._fold_gen += 1
        self._fold_deletes = set()
        tp("daut_drop", gen=self._fold_gen)

    def rebuild(self, hash_buckets: int = 0) -> None:
        """Fold the delta into a fresh device automaton snapshot
        (synchronous; see ``background_rebuild`` for the no-stall path).

        If a background build is in flight, wait for it first: two
        concurrent builders would interleave TokenDict.add's
        check-then-act and could alias two words onto one token id."""
        t = self._build_thread
        if t is not None and t.is_alive():
            t.join()
        self._poll_swap()
        inputs = self._snapshot_inputs()
        (
            self._aut,
            self._dev,
            self._fid_arr,
            self._n_base,
            self._build_cache,
        ) = self._build(inputs, hash_buckets=hash_buckets)
        self._delta = {}
        self._delta_seq = {}
        self._residual_log = []
        self._residual_count = 0
        self._fold_watermark = self._wild.last_seq()
        self._drop_delta_aut()
        self._deleted_base = set()
        self._deleted_daut = set()

    def kick_rebuild(self) -> bool:
        """Start a background rebuild NOW if the delta has outgrown
        the threshold — the olp ladder's recovery kick for rebuilds
        deferred during overload (a stable fleet may otherwise never
        mutate again, leaving the oversized delta tiers serving every
        window forever).  Returns True when one was started."""
        if (
            self.background_rebuild
            and len(self._delta) >= self.rebuild_threshold
            and not self._building
        ):
            self._start_background_rebuild()
            return True
        return False

    def _start_background_rebuild(self) -> None:
        with self._lock:
            if self._building:
                return
            self._building = True
            self._pending_inserts = []
            self._pending_deletes = set()
            self._rebuild_snap_seq = self._wild.last_seq()
            inputs = self._snapshot_inputs()
        # sharded engines snapshot a plain filter list, the base engine
        # encoded arrays — count accordingly (and BEFORE the try, so the
        # failure handler can never raise and wedge `_building`)
        n_filters = (
            len(inputs[1]) if isinstance(inputs, tuple) else len(inputs)
        )

        def work():
            try:
                t_build = time.perf_counter()
                built = self._build(inputs, device_put=True)
                # compile the kernel for the new table shapes HERE, in
                # the builder thread, so the first post-swap match never
                # pays a shape-class compile in its own latency
                try:
                    if built[1] is not None and built[0].n_nodes > 1:
                        self._warm_built(built[0], built[1])
                except Exception:
                    import logging

                    logging.getLogger("emqx_tpu.engine").debug(
                        "base shape warm failed", exc_info=True
                    )
                prof = self.profiler
                if prof is not None:
                    prof.event(
                        "rebuild", time.perf_counter() - t_build,
                        n_filters=n_filters,
                    )
            except Exception:  # build failure must not wedge the engine
                import logging

                logging.getLogger("emqx_tpu.engine").exception(
                    "background automaton rebuild failed "
                    "(%d filters); matching continues on the host overlay",
                    n_filters,
                )
                built = ()
            with self._lock:
                self._built = built

        self._build_thread = threading.Thread(
            target=work, name="matchengine-rebuild", daemon=True
        )
        self._build_thread.start()

    def _poll_swap(self) -> None:
        """Adopt a finished background build: O(pending) swap, no stall."""
        if self._built is None:
            return
        with self._lock:
            built = self._built
            self._built = None
            if not built:  # failed build: allow a retrigger
                self._building = False
                return
            (
                self._aut,
                self._dev,
                self._fid_arr,
                self._n_base,
                self._build_cache,
            ) = built
            delta: Dict[Hashable, Tuple[str, ...]] = {}
            for flt, fid in self._pending_inserts:
                if self._by_fid.get(fid) == flt and fid not in self._deep:
                    delta[fid] = tuple(flt.split("/"))
            self._delta = delta
            # pending inserts become the fresh residual: the new base
            # covers everything up to the build snapshot, so the
            # watermark moves to the snapshot's sequence point and the
            # log keeps only what arrived after it
            self._delta_seq = {
                fid: s for fid, s in self._delta_seq.items() if fid in delta
            }
            self._fold_watermark = self._rebuild_snap_seq
            # rebuild the log from _delta_seq, NOT the old log: a fold
            # committing mid-build pruned the log past ITS watermark,
            # which is ahead of the rebuild snapshot — every pending
            # delta entry post-dates the snapshot, so all are residual
            self._residual_log = [
                (fid, s) for fid, s in self._delta_seq.items()
            ]
            self._residual_count = len(self._residual_log)
            self._drop_delta_aut()
            # unconditional: membership against the arena would race
            # its in-place mutation; masking absent fids is harmless
            self._deleted_base = set(self._pending_deletes)
            self._deleted_daut = set()
            self._pending_inserts = []
            self._pending_deletes = set()
            self._building = False
            tp("base_swap", pending=len(delta))

    def warmup(self, max_batch: int = 4096) -> int:
        """Pre-compile the kernel for every power-of-two batch bucket up
        to ``max_batch`` (the `_pad_batch` shape set), so a production
        publish flood never stalls on a first-use XLA compile.  Returns
        the number of buckets warmed (0 when the device path is off)."""
        with self._mlock:
            device_on = (
                self.use_device is not False
                and self._aut is not None
                and self._aut.n_nodes > 1
            )
        if not device_on:
            return 0
        n = 0
        bp = 16
        # pin the device for the warmup sweep: in auto mode the policy
        # would route the small synthetic windows to the host, leaving
        # kernel buckets cold AND the device-cost EWMAs unseeded (the
        # first LIVE window would then pay the measurement probe as
        # head-of-line latency)
        self._warmup_force = True
        try:
            while bp <= max_batch:
                self.match_batch(["\x00warmup"] * bp)
                n += 1
                bp *= 2
            # the sweep's first-use compiles polluted the device-cost
            # EWMAs (a 2 s compile window is not a 100 ms steady-state
            # window): reseed from one more WARM window of DISTINCT
            # topics (a fully-deduped window hides the real per-topic
            # encode/expand cost) so the auto policy starts from
            # representative numbers
            self._dev_window_s = None
            self._dev_cpu_us = None
            self.match_batch(
                [f"\x00warmup/{i}" for i in range(min(1024, max_batch))]
            )
        finally:
            self._warmup_force = False
        return n

    def index_stats(self) -> Dict[str, object]:
        return {
            "base": self._n_base,
            "delta": len(self._delta),
            "folded": len(self._daut_fids),
            "residual": self._residual_count,
            "deep": len(self._deep),
            "exact": sum(len(v) for v in self._exact.values()),
            "deleted": len(self._deleted_base) + len(self._deleted_daut),
            "building": self._building,
            "folding": self._folding,
            "auto_host_windows": self._auto_stats["host_windows"],
            "auto_dev_windows": self._auto_stats["dev_windows"],
            "breaker_open": self._brk_open,
            "breaker_trips": self._brk_stats["trips"],
            "breaker_device_errors": self._brk_stats["device_errors"],
            "host_us_ewma": self._host_us,
            "dev_cpu_us_ewma": self._dev_cpu_us,
            "dev_window_ms_ewma": (
                self._dev_window_s * 1e3
                if self._dev_window_s is not None else None
            ),
        }

    def _device_tables(self):
        if self._dev is None:
            # LAZY path (upload-failed / toggled corners): runs under
            # _mlock on a match thread — no inter-chunk throttling
            # here, or the sleeps would hold the lock and stall every
            # SUBSCRIBE/match for seconds; the background fold/build
            # uploads keep the throttled default
            self._dev = self._device_put(self._aut, throttle=False)
        return self._dev

    # ---------------------------------------------------------- breaker

    def _device_failure(self, reason: str = "error") -> None:
        """Record one device-step failure; trips the breaker after
        `breaker_threshold` CONSECUTIVE ones.  Called from whatever
        thread ran the match — the trip callback must be thread-safe
        (the broker's is: it schedules onto the event loop)."""
        self._brk_stats["device_errors"] += 1
        self._brk_failures += 1
        if not self._brk_open and (
            self._brk_failures >= self.breaker_threshold
        ):
            self._trip_breaker(reason)

    def _device_ok(self, wall: float) -> None:
        """A device window completed.  A wall time past the watchdog
        deadline still counts as a failure: a wedged-but-eventually-
        returning device (tunnel stall, compile storm) must degrade to
        the host path, not hold every window hostage."""
        if (
            self.breaker_deadline is not None
            and wall > self.breaker_deadline
        ):
            self._brk_stats["slow_windows"] += 1
            self._device_failure(reason="deadline")
            return
        self._brk_failures = 0

    def _trip_breaker(self, reason: str) -> None:
        self._brk_open = True
        self._brk_opened_at = time.monotonic()
        self._brk_probe_last = self._brk_opened_at
        self._brk_stats["trips"] += 1
        info = {"reason": reason, "failures": self._brk_failures,
                "trips": self._brk_stats["trips"]}
        import logging

        logging.getLogger("emqx_tpu.engine").warning(
            "device-path breaker OPEN (%s after %d consecutive "
            "failures): matching degrades to host-only; background "
            "probe every %.1fs", reason, self._brk_failures,
            self.breaker_probe_interval,
        )
        tp("breaker_trip", reason=reason)
        if self.on_breaker_trip is not None:
            try:
                self.on_breaker_trip(info)
            except Exception:
                logging.getLogger("emqx_tpu.engine").exception(
                    "breaker trip callback failed"
                )

    def _close_breaker(self) -> None:
        self._brk_open = False
        self._brk_failures = 0
        info = {"open_for": time.monotonic() - self._brk_opened_at,
                "trips": self._brk_stats["trips"]}
        import logging

        logging.getLogger("emqx_tpu.engine").warning(
            "device-path breaker CLOSED after %.1fs: device matching "
            "re-enabled", info["open_for"],
        )
        tp("breaker_clear")
        if self.on_breaker_clear is not None:
            try:
                self.on_breaker_clear(info)
            except Exception:
                logging.getLogger("emqx_tpu.engine").exception(
                    "breaker clear callback failed"
                )

    def _brk_maybe_probe(self) -> None:
        """While the breaker is open, re-try the device path out-of-
        band on a one-shot daemon thread (never as head-of-line latency
        in the live window stream); success re-closes the breaker."""
        now = time.monotonic()
        if (
            self._brk_probing
            or now - self._brk_probe_last < self.breaker_probe_interval
        ):
            return
        self._brk_probing = True
        self._brk_probe_last = now
        sample = list(self._probe_topics[:64]) or [
            f"\x00brkprobe/{i}" for i in range(64)
        ]

        def work() -> None:
            ok = False
            try:
                errs0 = self._brk_stats["device_errors"]
                pending = self.match_batch_submit(
                    sample, _force_device=True
                )
                self.match_batch_finish(pending)
                # success = the submit really chose the device ("host"
                # means it fell back internally) AND the finish side
                # recorded no new failure — finish catches its own
                # transfer faults and returns host results without
                # raising, which must NOT close the breaker
                ok = (
                    pending[0] == "dev"
                    and self._brk_stats["device_errors"] == errs0
                )
            except Exception:
                ok = False
            finally:
                self._brk_stats["probes"] += 1
                self._brk_probing = False
            if ok and self._brk_open:
                self._close_breaker()

        threading.Thread(
            target=work, name="engine-brk-probe", daemon=True
        ).start()

    @property
    def breaker_open(self) -> bool:
        return self._brk_open

    def breaker_info(self) -> Dict[str, object]:
        return {
            "open": self._brk_open,
            "consecutive_failures": self._brk_failures,
            "threshold": self.breaker_threshold,
            "probe_interval": self.breaker_probe_interval,
            "deadline": self.breaker_deadline,
            **self._brk_stats,
        }

    def stats(self) -> Dict[str, object]:
        """The engine's full gauge surface for exposition (Prometheus
        scrape, OTLP metrics, $SYS): index tier sizes, auto-policy
        window counts, the cost EWMAs and breaker state."""
        out = self.index_stats()
        out["auto_probes"] = self._auto_stats["probes"]
        out["breaker_slow_windows"] = self._brk_stats["slow_windows"]
        out["breaker_probes"] = self._brk_stats["probes"]
        out["decide_host_windows"] = self._dec_stats["host_windows"]
        out["decide_dev_windows"] = self._dec_stats["dev_windows"]
        out["decide_dev_errors"] = self._dec_stats["dev_errors"]
        out["rules_host_windows"] = self._rul_stats["host_windows"]
        out["rules_dev_windows"] = self._rul_stats["dev_windows"]
        out["rules_dev_errors"] = self._rul_stats["dev_errors"]
        out["rules_host_us_ewma"] = self._rul_host_us
        out["rules_dev_us_ewma"] = self._rul_dev_us
        return out

    # -------------------------------------------------------------- match

    def match(self, topic: str) -> Set[Hashable]:
        return self.match_batch([topic])[0]

    def match_host(self, topic_words: T.Words) -> Set[Hashable]:
        """Pure-host exact match (oracle path)."""
        out = set(self._exact.get(T.join(topic_words), ()))
        out |= self._wild.match_words(topic_words)
        return out

    def _snapshot_refs(self) -> Tuple:
        """Coherent (automaton, device tables, fid array, residual
        delta, deep, deleted, delta-automaton triple) snapshot; must be
        captured under ``_mlock`` so a concurrent rebuild swap cannot
        mix generations.  delta/deleted belong to the SAME generation as
        the automata: a swap landing mid-kernel replaces them with
        (empty) successors folded into the new base, and overlaying
        those against the old base would drop every delta-resident
        subscription for the window."""
        if self._daut is not None and self._ddev is None:
            import jax

            # lazy upload keeps device_put off the insert path (folds
            # usually stage device arrays themselves; this covers the
            # upload-failed / use_device-toggled corners)
            self._ddev = tuple(
                jax.device_put(a) for a in self._daut.device_arrays()
            )
        return (
            self._aut,
            self._device_tables(),
            self._fid_arr,
            _ResidualView(self._wild, self._fold_watermark),
            self._deep,
            self._deleted_base,
            (self._daut, self._ddev, self._dfid_arr),
            self._deleted_daut,
        )

    def _auto_choose(self, n: int, congested: bool) -> bool:
        """Pick host (False) or device (True) for an auto-mode window
        of ``n`` topics from the measured cost EWMAs.  Device cost is
        HONEST HOST CPU (thread_time): on a link whose transfer wait
        burns cycles (the axon tunnel client) the device path shows
        its true cost and host wins; co-located (DMA transfers, GIL
        released) the device cost collapses and the policy flips.
        While host is chosen, `_maybe_probe` keeps the device numbers
        fresh out-of-band."""
        self._auto_seq += 1
        host_us = self._host_us if self._host_us is not None else 5.0
        if self._dev_window_s is None:
            # unmeasured: serve on host; warmup() seeds the estimates
            # at boot, and the probe below fires if host degrades
            use_dev = False
        elif congested:
            # throughput mode: pipelining hides most of a device
            # window's wall, but the window still occupies an ordered-
            # dispatch slot for ~RTT/depth — a stall every HOST window
            # queued behind it pays too.  Effective per-topic device
            # cost = host-side CPU + that amortized slot: over a
            # high-RTT link small windows stay host (49µs/topic of
            # slot cost at n=512/RTT=100ms dwarfs the trie), while
            # co-located the slot term vanishes and big windows
            # offload (0.7µs at RTT=1.5ms).  The 1.2 margin resists
            # path flapping, whose head-of-line mixing cost neither
            # estimate sees.
            dev_cpu = (
                self._dev_cpu_us if self._dev_cpu_us is not None else 2.0
            )
            slot_us = (
                self._dev_window_s / 4.0 / max(n, 1) * 1e6
            )
            use_dev = host_us > (dev_cpu + slot_us) * 1.2
        else:
            # latency mode: the window resolves when the caller gets
            # the result back — compare wall times
            use_dev = n * host_us * 1e-6 > self._dev_window_s
        if not use_dev:
            # refresh the device numbers out-of-band: aggressively
            # (30 s) when there is a live case for switching
            # (congestion + an expensive host trie), lazily (120 s)
            # otherwise — without the lazy tick a transient device
            # slowdown would pin the policy to host FOREVER, because
            # host windows never re-measure the device
            self._maybe_probe(
                urgent=congested and host_us > 15.0
            )
        return use_dev

    def _maybe_probe(self, urgent: bool = False) -> None:
        """Refresh the device EWMAs off-band (30 s cadence when a
        switch is plausible, 120 s maintenance otherwise), on a
        one-shot daemon thread, over recent real topics."""
        now = time.monotonic()
        interval = 30.0 if urgent else 120.0
        if (
            self._probe_running
            or now - self._probe_last < interval
            or not self._probe_topics
        ):
            return
        self._probe_running = True
        self._probe_last = now
        sample = list(self._probe_topics)

        def work() -> None:
            try:
                self._warmup_probe(sample)
            except Exception:
                pass
            finally:
                self._probe_running = False

        threading.Thread(
            target=work, name="engine-dev-probe", daemon=True
        ).start()

    def _warmup_probe(self, topics: List[str]) -> None:
        """One measured device window (submit+finish) outside the live
        window stream; updates the device EWMAs.  Uses the explicit
        force flag, NOT _warmup_force — that one is instance-wide and
        would shunt concurrent live windows onto the device."""
        while 0 < len(topics) < 64:
            topics = topics + topics  # EWMA gate needs >=64 topics
        pending = self.match_batch_submit(topics, _force_device=True)
        self.match_batch_finish(pending)
        self._auto_stats["probes"] += 1

    # ------------------------------------------ window decide columns

    def decide_window(
        self,
        cols: Tuple,
        rev: int,
        opts_rows: np.ndarray,
        client_rows: np.ndarray,
        msg_idx: np.ndarray,
        m_qos: np.ndarray,
        m_retain: np.ndarray,
        m_from_row: np.ndarray,
    ) -> Tuple[np.ndarray, str]:
        """Compute one window's packed per-delivery decision column
        (see ops.match_kernel's bit layout) on the host or the device,
        chosen per window by the measured per-delivery cost EWMAs.

        ``cols`` are the router's SubOpts attribute columns and ``rev``
        their mutation counter (the device copies cache on it).  A
        device fault degrades THIS window to the bit-identical numpy
        twin and counts against the shared PR 1 circuit breaker, so a
        dead device path trips matching AND deciding to host-only
        together; the background breaker probe heals both."""
        n = len(opts_rows)
        if n and self._decide_choose(n):
            try:
                t0 = time.perf_counter()
                packed = self._decide_device(
                    cols, rev, opts_rows, client_rows, msg_idx,
                    m_qos, m_retain, m_from_row,
                )
                us = (time.perf_counter() - t0) * 1e6 / n
                if self._dec_dev_warm:
                    self._dec_dev_us = (
                        us if self._dec_dev_us is None
                        else 0.2 * us + 0.8 * self._dec_dev_us
                    )
                else:
                    # first device window: the JIT compile dominated
                    # the wall time — warm only, don't record
                    self._dec_dev_warm = True
                self._dec_stats["dev_windows"] += 1
                return packed, "dev"
            except Exception:
                self._dec_stats["dev_errors"] += 1
                self._device_failure("decide")
                import logging

                logging.getLogger("emqx_tpu.engine").exception(
                    "device decide step failed for window of %d; "
                    "host columns", n,
                )
        from .ops.match_kernel import decide_batch_host

        t0 = time.perf_counter()
        packed = decide_batch_host(
            *cols, opts_rows, client_rows, msg_idx,
            m_qos, m_retain, m_from_row,
        )
        if n:
            us = (time.perf_counter() - t0) * 1e6 / n
            self._dec_host_us = (
                us if self._dec_host_us is None
                else 0.2 * us + 0.8 * self._dec_host_us
            )
        self._dec_stats["host_windows"] += 1
        return packed, "host"

    def _decide_choose(self, n: int) -> bool:
        """Host (False) or device (True) for a decide window of ``n``
        deliveries.  ``decide_force`` pins the path (tests / property
        suites); the breaker overrides everything but a host pin."""
        force = self.decide_force
        if force is not None:
            return force == "dev" and not self._brk_open
        if self._brk_open or self.use_device is False:
            return False
        if self.use_device is True:
            return True
        # auto: the columns are one elementwise pass, so the host twin
        # wins until windows are large enough to amortize a dispatch —
        # measure rather than guess, seeding the device EWMA on the
        # first big window
        self._dec_seq += 1
        host = self._dec_host_us if self._dec_host_us is not None else 0.05
        dev = self._dec_dev_us
        if dev is None:
            use_dev = n >= 4096
        elif n >= 512 and host > dev * 1.2:
            use_dev = True
        else:
            # periodic in-band re-probe on a big window so a
            # transient device slowdown can't pin the policy to host
            # forever (host windows never re-measure the device)
            use_dev = (
                n >= 4096
                and self._dec_seq - self._dec_probe_seq >= 1024
            )
        if use_dev:
            self._dec_probe_seq = self._dec_seq
        return use_dev

    def _decide_device(
        self, cols, rev, opts_rows, client_rows, msg_idx,
        m_qos, m_retain, m_from_row,
    ) -> np.ndarray:
        """One device decide step: upload the attribute columns (cached
        by ``rev``), pad the delivery/message columns to power-of-two
        buckets (bounded shape classes, as `_pad_batch` does for the
        match kernel), run the fused kernel, slice the padding off."""
        from .ops.match_kernel import decide_batch

        if failpoints.enabled:
            # chaos seam: an injected error degrades this window to the
            # host columns and feeds the shared device breaker
            failpoints.evaluate("dispatch.decide.device")
        cache = self._dec_cols_cache
        if cache is None or cache[0] != rev:
            import jax

            cache = (rev, tuple(jax.device_put(c) for c in cols))
            self._dec_cols_cache = cache
        n = len(opts_rows)
        npad = 64
        while npad < n:
            npad *= 2
        b = len(m_qos)
        bpad = 16
        while bpad < b:
            bpad *= 2

        def pad(a, cap, fill, dtype):
            out = np.full(cap, fill, dtype=dtype)
            out[: len(a)] = a
            return out

        packed = decide_batch(
            *cache[1],
            pad(opts_rows, npad, 0, np.int32),
            pad(client_rows, npad, -1, np.int32),
            pad(msg_idx, npad, 0, np.int32),
            pad(m_qos, bpad, 0, np.int8),
            pad(m_retain, bpad, False, bool),
            pad(m_from_row, bpad, -1, np.int32),
        )
        return np.asarray(packed)[:n]

    # -------------------------------------- rules x window matrix

    def rules_eval_window(self, stack, rev: int, cols, rows=None):
        """Evaluate the rule registry's stacked WHERE program against
        one window's column planes: the ``[n_rules, n_msgs]`` boolean
        pass matrix, host numpy twin or the fused device kernel
        chosen per window by the measured per-cell cost EWMAs.

        ``stack`` is a `rules.predicate.StackedRules`, ``rev`` the
        rule engine's mutation counter (the device program-array
        cache keys on it), ``cols`` a `rules.columns.WindowColumns`.
        ``rows`` (sorted int array) names the matrix rows whose rules
        actually matched this window's topics: the host twin
        row-slices the program to just those and scatters back (a
        partitioned 10k-rule registry evaluates only the matched
        slice), while the device path keeps the full rev-cached
        program upload.  A device fault degrades THIS window to the
        bit-identical host twin and counts against the shared PR 1
        circuit breaker, so a dead device path trips matching,
        deciding and rule eval to host together; the background
        breaker probe heals all three."""
        n_active = stack.n_rules if rows is None else len(rows)
        n = n_active * cols.n
        if n and self._rules_choose(stack, cols, n):
            try:
                t0 = time.perf_counter()
                mat = self._rules_device(stack, rev, cols)
                us = (time.perf_counter() - t0) * 1e6 / n
                if self._rul_dev_warm:
                    self._rul_dev_us = (
                        us if self._rul_dev_us is None
                        else 0.2 * us + 0.8 * self._rul_dev_us
                    )
                else:
                    # first device window: JIT compile dominated the
                    # wall time — warm only, don't record
                    self._rul_dev_warm = True
                self._rul_stats["dev_windows"] += 1
                return mat, "dev"
            except Exception:
                self._rul_stats["dev_errors"] += 1
                self._device_failure("rules")
                import logging

                logging.getLogger("emqx_tpu.engine").exception(
                    "device rules eval failed for %dx%d matrix; "
                    "host columns", stack.n_rules, cols.n,
                )
        from .ops.match_kernel import rules_eval_host

        t0 = time.perf_counter()
        if rows is not None and n_active < stack.n_rules:
            sub = rules_eval_host(
                stack.code[rows], stack.a0[rows], stack.a1[rows],
                stack.a2[rows], stack.a3[rows], stack.litn[rows],
                cols.lit_ranks, stack.last[rows],
                cols.num, cols.sid, cols.err, cols.prs,
            )
            mat = np.zeros((stack.n_rules, cols.n), bool)
            mat[rows] = sub
        else:
            mat = rules_eval_host(
                stack.code, stack.a0, stack.a1, stack.a2, stack.a3,
                stack.litn, cols.lit_ranks, stack.last,
                cols.num, cols.sid, cols.err, cols.prs,
            )
        if n:
            us = (time.perf_counter() - t0) * 1e6 / n
            self._rul_host_us = (
                us if self._rul_host_us is None
                else 0.2 * us + 0.8 * self._rul_host_us
            )
        self._rul_stats["host_windows"] += 1
        return mat, "host"

    def _rules_choose(self, stack, cols, n: int) -> bool:
        """Host (False) or device (True) for an ``n``-cell rules
        matrix.  `rules_force` pins the path (tests / benches); the
        breaker overrides everything but a host pin, and scheduling a
        heal probe here keeps a rules-heavy broker from staying
        host-pinned forever; the f32 gate (arith programs, f32-lossy
        literals or columns) protects the float64 oracle semantics."""
        force = self.rules_force
        if self._brk_open:
            self._brk_maybe_probe()
            return False
        if force == "host":
            return False
        if force is None and self.use_device is False:
            return False
        # resolve the COST decision before the f32 gate: the gate's
        # full-plane scan is O(P x W), and a window the policy would
        # serve on host anyway must not pay it
        if force == "dev" or self.use_device is True:
            use_dev = True
        else:
            self._rul_seq += 1
            host = (
                self._rul_host_us
                if self._rul_host_us is not None else 0.02
            )
            dev = self._rul_dev_us
            if dev is None:
                use_dev = n >= 16384
            elif n >= 2048 and host > dev * 1.2:
                use_dev = True
            else:
                # periodic in-band re-probe on a big matrix so a
                # transient device slowdown can't pin the policy to
                # host forever
                use_dev = (
                    n >= 16384
                    and self._rul_seq - self._rul_probe_seq >= 1024
                )
            if use_dev:
                self._rul_probe_seq = self._rul_seq
        if not use_dev:
            return False
        # the f32 gate binds even under a dev pin: the device kernel
        # cannot produce float64-correct results for these windows
        if stack.has_arith or not stack.f32_lits_safe:
            return False
        # only the WHERE planes reach the device kernel (they are a
        # prefix of the combined WHERE+SELECT path union); SELECT-only
        # columns stay on the float64 numpy materialization
        return cols.f32_safe(len(stack.paths))

    def _rules_device(self, stack, rev: int, cols) -> np.ndarray:
        """One device rules step: upload the stacked program (cached
        by the registry's ``rev``), pad rules/window to power-of-two
        buckets (bounded shape classes, as `_decide_device` does),
        run the fused kernel, slice the padding off."""
        from .ops.match_kernel import rules_eval_batch

        if failpoints.enabled:
            # chaos seam: an injected error degrades this window to
            # the host twin and feeds the shared device breaker
            failpoints.evaluate("dispatch.rules.device")
        r_n, w_n = stack.n_rules, cols.n
        rpad = 8
        while rpad < r_n:
            rpad *= 2
        wpad = 16
        while wpad < w_n:
            wpad *= 2

        def padr(a, fill, dtype):  # [R, S] -> [rpad, S]
            out = np.full((rpad,) + a.shape[1:], fill, dtype=dtype)
            out[: a.shape[0]] = a
            return out

        cache = self._rul_prog_cache
        if cache is None or cache[0] != (rev, rpad):
            import jax

            prog = (
                padr(stack.code, 0, np.int32),
                padr(stack.a0, -1, np.int32),
                padr(stack.a1, -1, np.int32),
                padr(stack.a2, -1, np.int32),
                padr(stack.a3, -1, np.int32),
                padr(stack.litn, 0.0, np.float32),
                padr(stack.last, 0, np.int32),
            )
            cache = (
                (rev, rpad),
                tuple(jax.device_put(a) for a in prog),
            )
            self._rul_prog_cache = cache
        code, a0, a1, a2, a3, litn, last = cache[1]

        def padw(a, fill, dtype):  # [P, W] -> [max(P,1), wpad]
            out = np.full(
                (max(a.shape[0], 1), wpad), fill, dtype=dtype
            )
            out[: a.shape[0], :w_n] = a
            return out

        lit_ranks = cols.lit_ranks
        if lit_ranks.size == 0:
            lit_ranks = np.zeros(1, np.int32)
        mat = rules_eval_batch(
            code, a0, a1, a2, a3, litn, lit_ranks, last,
            padw(cols.num, np.nan, np.float32),
            padw(cols.sid, -1, np.int32),
            padw(cols.err, False, bool),
            padw(cols.prs, False, bool),
        )
        return np.asarray(mat)[:r_n, :w_n]

    def match_batch(
        self, topics: Sequence[str], congested: bool = False
    ) -> List[Set[Hashable]]:
        """Staged so the device step runs lock-free on an immutable
        snapshot: encode/snapshot under the mutation lock, kernel
        outside it, overlay (exact/delta/deep/deleted — possibly newer
        than the snapshot, which only *adds* correctness) under it
        again.

        ``use_device=None`` (the broker default) resolves host-vs-
        device PER WINDOW via `_auto_choose`; True/False pin the path
        (benches and tests rely on the pinned behavior)."""
        return self.match_batch_finish(
            self.match_batch_submit(topics, congested)
        )

    def match_batch_submit(
        self, topics: Sequence[str], congested: bool = False,
        _force_device: bool = False,
    ):
        """Phase 1: decide the path, and for a device window ENCODE +
        DISPATCH the kernels without waiting (JAX async dispatch).
        The pending handle this returns pipelines: the broker submits
        windows N+1..N+k while window N's transfer streams back, so
        e2e throughput amortizes the host<->device round-trip from ONE
        thread — executor-thread concurrency does NOT overlap the
        transfer wait (the blocking conversion serializes), async
        dispatch does (the standalone bench's depth-8 scheme)."""
        prof = self.profiler
        if prof is not None and prof.enabled:
            _t_tok = time.perf_counter()
            words = [T.words(t) for t in topics]
            prof.stage("tokenize", time.perf_counter() - _t_tok)
        else:
            words = [T.words(t) for t in topics]
        with self._mlock:
            if self._built is not None:
                self._poll_swap()
            device_capable = (
                self.use_device is not False
                and self._aut is not None
                and self._aut.n_nodes > 1
            )
            if device_capable and self._brk_open and not _force_device:
                # breaker open: host-only until the background probe
                # re-closes it (failure-driven degradation)
                device_capable = False
                self._brk_maybe_probe()
            if _force_device and device_capable:
                device_on = True
            elif device_capable and self.use_device is None:
                device_on = (
                    True if self._warmup_force
                    else self._auto_choose(len(words), congested)
                )
            else:
                device_on = device_capable
            snap_failed = False
            if device_on:
                try:
                    snap = self._snapshot_refs()
                except Exception:
                    # lazy device upload failed: a device fault, so it
                    # feeds the breaker and the window serves on host
                    import logging

                    logging.getLogger("emqx_tpu.engine").exception(
                        "device snapshot failed; window falls back to "
                        "host matching"
                    )
                    device_on = False
                    snap_failed = True
                    self._device_failure()
                else:
                    tp("match_snapshot",
                       watermark=self._fold_watermark)
        if not device_on:
            # per-topic locking: holding _mlock across the whole batch
            # would stall a loop-thread SUBSCRIBE (and with it the
            # entire event loop) for the full window when this runs in
            # the batcher's executor
            c0 = time.thread_time()
            out: List[Set[Hashable]] = []
            for ws in words:
                with self._mlock:
                    out.append(self.match_host(ws))
            if device_capable and len(words) >= 64:
                us = (time.thread_time() - c0) / len(words) * 1e6
                self._host_us = (
                    us if self._host_us is None
                    else 0.8 * self._host_us + 0.2 * us
                )
                self._auto_stats["host_windows"] += 1
                # keep a fresh sample for the out-of-band device probe
                # (small: each probe's host-side cost is paid in GIL)
                self._probe_topics = list(topics[:256])
            return ("host-fallback" if snap_failed else "host", out)
        t0 = time.perf_counter()
        c0 = time.thread_time()
        try:
            # dispatch the delta kernel FIRST (async JAX dispatch) so
            # the small fixed-shape call overlaps the base kernel +
            # transfer
            daut, ddev, _ = snap[6]
            dpend = (
                self._flat_dispatch(daut, ddev, words)
                if daut is not None
                else None
            )
            pend_base = self._flat_submit(snap, words)
        except Exception:
            # a dispatch-side device fault (encode upload, compile,
            # injected engine.device_step error): count it toward the
            # breaker and serve THIS window on the host oracle —
            # per-topic locking, as in the host branch above.  The
            # distinct tag keeps the profiler's path attribution
            # honest: this window is a FALLBACK, not a policy choice
            import logging

            logging.getLogger("emqx_tpu.engine").exception(
                "device dispatch failed for window of %d; host "
                "fallback", len(words),
            )
            self._device_failure()
            out = []
            for ws in words:
                with self._mlock:
                    out.append(self.match_host(ws))
            return ("host-fallback", out)
        if len(words) >= 64:
            # keep a fresh sample for the breaker probe: after a trip
            # the device path stops running, and probing with recent
            # REAL topics measures what production windows would see
            self._probe_topics = list(topics[:256])
        cpu0 = time.thread_time() - c0  # encode + dispatch CPU
        return ("dev", snap, pend_base, dpend, topics, words, t0, cpu0)

    def _flat_submit(self, snap: Tuple, words: Sequence[T.Words]):
        """Overridable async-dispatch hook for the base snapshot:
        subclasses whose flat path is synchronous (the sharded mesh
        engine's shard_map call) override this to compute eagerly."""
        return ("pend", self._flat_dispatch(snap[0], snap[1], words))

    def _flat_result(self, token):
        kind, v = token
        return self._flat_finish(v) if kind == "pend" else v

    def match_batch_finish(self, pending, info=None) -> List[Set[Hashable]]:
        """Phase 2: wait for the device results (if any), overlay the
        host tiers, update the auto-policy cost EWMAs.  CPU is
        accounted with thread_time so a transfer wait that BURNS
        cycles (tunnel client polling) is charged to the device path
        honestly, while a true DMA wait (co-located hardware, GIL
        released) is not.

        ``info`` (optional dict) receives ``path``: the path that
        ACTUALLY served the window — ``dev``, ``host``, or
        ``host-fallback`` when a device fault degraded it here — so
        the profiler's flight record never labels a fallback window
        as a device window."""
        if pending[0] != "dev":
            if info is not None:
                info["path"] = pending[0]
            return pending[1]
        if info is not None:
            info["path"] = "dev"
        _, snap, pend_base, dpend, topics, words, t0, cpu0 = pending
        t1w = time.perf_counter()
        c1 = time.thread_time()
        try:
            rows, gpos, ovf = self._flat_result(pend_base)
            dflat = (
                self._flat_finish(dpend) if dpend is not None else None
            )
        except Exception:
            # the wait/transfer side of the device step failed: breaker
            # food, and the window re-matches on the host oracle
            import logging

            logging.getLogger("emqx_tpu.engine").exception(
                "device result failed for window of %d; host fallback",
                len(words),
            )
            self._device_failure()
            if info is not None:
                info["path"] = "host-fallback"
            return self.match_batch_host(list(topics))
        self._device_ok(time.perf_counter() - t0)
        tp("match_overlay")
        with self._mlock:
            out = self._overlay(topics, words, rows, gpos, ovf, snap, dflat)
        if self.use_device is None and len(words) >= 64:
            cpu_us = (
                (cpu0 + time.thread_time() - c1) / len(words) * 1e6
            )
            self._dev_cpu_us = (
                cpu_us if self._dev_cpu_us is None
                else 0.8 * self._dev_cpu_us + 0.2 * cpu_us
            )
            # the wall EWMA feeds LATENCY-mode decisions, so it must
            # estimate a SOLO window's round trip.  Only unqueued
            # windows (finish started right after submit) qualify:
            # a pipelined window's submit→finish wall includes time
            # queued behind predecessors (charging that to the device
            # disabled it with its own backlog — review r5), while its
            # finish-only wall UNDER-estimates (the transfer already
            # streamed during the queue wait) and flipped quiet
            # windows onto the device.
            if t1w - t0 < 0.005:
                wall = time.perf_counter() - t0
                self._dev_window_s = (
                    wall if self._dev_window_s is None
                    else 0.8 * self._dev_window_s + 0.2 * wall
                )
            self._auto_stats["dev_windows"] += 1
        return out

    def match_batch_host(self, topics: Sequence[str]) -> List[Set[Hashable]]:
        """Pure-host batch match (the device-failure fallback path)."""
        out: List[Set[Hashable]] = []
        for t in topics:
            with self._mlock:
                out.append(self.match_host(T.words(t)))
        return out

    def _overlay(
        self, topics, words, rows, gpos, ovf, snap, dflat=None
    ) -> List[Set[Hashable]]:
        fid_arr, delta, deep = snap[2], snap[3], snap[4]
        deleted_base, deleted_daut = snap[5], snap[7]
        fids_flat = fid_arr[gpos]
        per_row = np.bincount(rows, minlength=len(words))
        chunks = np.split(fids_flat, np.cumsum(per_row)[:-1])
        dchunks = None
        if dflat is not None:
            drows, dgpos, dovf = dflat
            dflat_fids = snap[6][2][dgpos]
            dper = np.bincount(drows, minlength=len(words))
            dchunks = np.split(dflat_fids, np.cumsum(dper)[:-1])
            ovf = ovf | dovf  # either kernel overflowing -> host row
        out: List[Set[Hashable]] = []
        for i, ws in enumerate(words):
            if ovf[i]:
                out.append(self.match_host(ws))
                continue
            # tombstones are per-generation: a fid deleted from the base
            # may live on (re-inserted) in the delta automaton, so each
            # kernel's chunk is masked by ITS OWN deleted set only
            fids: Set[Hashable] = set(chunks[i].tolist())
            if deleted_base:
                fids -= deleted_base
            if dchunks is not None:
                dfids = set(dchunks[i].tolist())
                if deleted_daut:
                    dfids -= deleted_daut
                fids |= dfids
            if self._exact:
                fids |= self._exact.get(topics[i], set())
            if len(delta):
                fids |= delta.match_words(ws)
            if len(deep):
                fids |= deep.match_words(ws)
            out.append(fids)
        return out

    def match_batch_flat(self, words: Sequence[T.Words]):
        """Device fast path: encoded topics -> flat row-sorted
        ``(topic_row, position)`` pairs into the base snapshot plus a
        per-row overflow flag.  The device ships only the compact code
        form; fan-out expansion happens host-side with vectorized CSR
        (`expand_codes_host`) — the SURVEY §7 amplification strategy.
        Rows flagged ``ovf`` must be re-matched on the host.  Callers
        must still overlay exact/delta/deep/deleted state."""
        with self._mlock:
            snap = self._snapshot_refs()
        return self._flat_from_snapshot(snap, words)

    def _flat_from_snapshot(self, snap: Tuple, words: Sequence[T.Words]):
        return self._flat_finish(self._flat_dispatch(snap[0], snap[1], words))

    def _encode_rows(self, words, levels: int):
        """Tokenize with a MATRIX row cache: live publish streams are
        Zipf-heavy, so the per-topic work collapses to one dict lookup
        yielding a row index, and the batch materializes as one numpy
        fancy-index gather instead of B per-row copies (the Python copy
        loop capped the full match path at ~⅓ of device throughput).
        Returns ``(idx, mat, lens, dol)`` — the row-index array doubles
        as the batch dedup key (`_flat_dispatch`).  The cache
        invalidates wholesale whenever the token dictionary grows (a
        previously-unknown word may now be a filter literal, making
        cached UNKNOWN rows stale)."""
        from .ops.dictionary import PAD_TOK

        with self._enc_mutex:
            gen = len(self._tdict)
            if gen != self._enc_gen:
                self._enc_cache.clear()
                self._enc_gen = gen
            def fresh_entry():
                cap = 4096
                return [
                    {},  # ws tuple -> row index
                    np.full((cap, levels), PAD_TOK, np.int32),
                    np.zeros(cap, np.int32),  # lengths
                    np.zeros(cap, bool),  # dollar
                    0,  # rows used
                ]

            entry = self._enc_cache.get(levels)
            if entry is None:
                entry = self._enc_cache[levels] = fresh_entry()
            # the hard-cap reset may only happen at a batch BOUNDARY,
            # and must allocate FRESH arrays: an in-flight batch on
            # another thread still gathers from the old ones after
            # releasing this mutex, so rows must never be overwritten
            # under it (growth and dict-clear paths already reallocate)
            elif entry[4] >= 262144:
                entry = self._enc_cache[levels] = fresh_entry()
            index, mat, lens, dol, used = entry
            b = len(words)
            # hit loop at C speed: one map() over the row cache (the
            # previous per-topic Python loop with numpy scalar stores
            # was ~1/3 of the full-path host cost)
            js = list(map(index.get, words))
            if None in js:
                miss_rows: Dict[Tuple[str, ...], int] = {}
                miss_ws: List[Tuple[str, ...]] = []
                for i, j in enumerate(js):
                    if j is None:
                        ws = words[i]
                        r = miss_rows.get(ws)
                        if r is None:
                            r = miss_rows[ws] = used + len(miss_ws)
                            miss_ws.append(ws)
                        js[i] = r
                need = used + len(miss_ws)
                while need > len(lens):  # grow by doubling
                    cap = len(lens) * 2
                    m2 = np.full((cap, levels), PAD_TOK, np.int32)
                    m2[: len(lens)] = mat
                    mat = m2
                    lens = np.resize(lens, cap)
                    dol = np.resize(dol, cap)
                    entry[1], entry[2], entry[3] = mat, lens, dol
                # _enc_mutex exists precisely to serialize the
                # native dictionary the first-use seeding touches
                # (see TokenDict.native's race note)
                # brokerlint: ignore[LOCK402]
                nat = self._tdict.native()
                if nat is not None and len(miss_ws) >= 16:
                    # batch the misses through the native tokenizer
                    # (GIL released, get-only lookups)
                    nat.encode_topics_into(
                        ["/".join(ws) for ws in miss_ws], levels,
                        mat[used:need], lens[used:need], dol[used:need],
                    )
                else:
                    get = self._tdict.get
                    for k, ws in enumerate(miss_ws):
                        n = min(len(ws), levels)
                        row = mat[used + k]
                        row[:] = PAD_TOK
                        for j2 in range(n):
                            row[j2] = get(ws[j2])
                        lens[used + k] = n
                        dol[used + k] = bool(ws) and ws[0].startswith("$")
                index.update(miss_rows)
                entry[4] = need
            idx = np.fromiter(js, np.int64, count=b)
            return idx, mat, lens, dol

    def _flat_dispatch(self, aut, tables, words: Sequence[T.Words]):
        """Encode + launch the kernel; returns a pending handle without
        blocking (JAX async dispatch), so several automata (base +
        segments) overlap on the device and the host<->device link.

        The batch is DEDUPLICATED first: publish windows are Zipf-heavy
        (hot topics repeat ~2x at bench scale), and matching each
        distinct topic once halves both the device step and the
        device->host code transfer.  The kernel returns the COMPACT
        layout (flat codes + int16 counts): the dense [B, m_cap] code
        matrix at a few-percent fill was the full-path bottleneck on
        links slower than PCIe (the axon tunnel moves ~10 MB/s)."""
        from .ops.match_kernel import match_batch_compact

        if failpoints.enabled:
            # chaos seam: error raises (breaker food), delay stalls the
            # step (watchdog food); evaluated per kernel dispatch
            failpoints.evaluate("engine.device_step")
        idx, mat, lens, dol = self._encode_rows(words, aut.kernel_levels)
        uniq, inv = np.unique(idx, return_inverse=True)
        tokens, lengths, dollar = _pad_batch(
            mat[uniq], lens[uniq], dol[uniq]
        )
        # compact-buffer capacity follows the observed fan-out: a live
        # broker window dedups to FEW unique topics each matching many
        # filters (100 uniques x fanout 9 overflows a 2x buffer), and
        # every clip costs a dense-kernel re-match — a second full
        # round-trip (+ possible compile) per window.  The multiplier
        # is sticky power-of-two (bounded shape-class ladder).
        c_cap = self._ccap_mult * tokens.shape[0]
        flat, counts, total = match_batch_compact(
            *tables,
            tokens,
            lengths,
            dollar,
            f_width=self.f_width,
            m_cap=self.m_cap,
            c_cap=c_cap,
        )
        # start device->host copies immediately: results stream back
        # while later dispatches (delta automaton, next windows) compute,
        # instead of serializing on the round-trip at finish time
        if hasattr(flat, "copy_to_host_async"):
            flat.copy_to_host_async()
            counts.copy_to_host_async()
            total.copy_to_host_async()
        return (
            aut, tables, flat, counts, total, (tokens, lengths, dollar),
            len(uniq), inv,
        )

    def _flat_finish(self, pending):
        from .ops.automaton import expand_codes_dedup, expand_codes_flat

        (aut, tables, flat, counts, total, enc, n_uniq, inv) = pending
        if int(np.asarray(total)[0]) > len(flat):
            # the compact buffer clipped: re-match this window on the
            # dense kernel — correct for any fill, just more bytes on
            # the wire — and DOUBLE the sticky capacity multiplier so
            # subsequent windows of this fan-out shape never clip
            # again.  The first clip at a given batch shape may pay
            # the dense kernel's compile; enable_compile_cache()
            # bounds that to once per shape EVER
            self._ccap_mult = min(self._ccap_mult * 2, 64)
            from .ops.match_kernel import match_batch

            codes, _, ovf = match_batch(
                *tables, *enc, f_width=self.f_width, m_cap=self.m_cap
            )
            rows, pos = expand_codes_dedup(
                aut.code_off, aut.code_idx,
                np.asarray(codes)[:n_uniq], inv,
            )
            return rows, pos, np.asarray(ovf)[:n_uniq][inv]
        counts = np.asarray(counts).astype(np.int64)
        ovf_u = counts < 0
        counts_pos = np.where(ovf_u, -counts - 1, counts)
        rows, pos = expand_codes_flat(
            aut.code_off, aut.code_idx, np.asarray(flat),
            counts_pos, inv,
        )
        return rows, pos, ovf_u[:n_uniq][inv]
