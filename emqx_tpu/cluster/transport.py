"""Inter-node RPC transport: length-prefixed JSON over asyncio TCP.

The gen_rpc analogue (/root/reference/apps/emqx/src/emqx_rpc.erl:82-119
wraps gen_rpc casts/calls): one listening server per node, one outgoing
connection per peer, messages are JSON objects with a ``type`` field
dispatched to registered handlers.  Casts are fire-and-forget (ordered
per peer, like gen_rpc's per-key ordered casts); calls carry a
``call_id`` and await a ``reply``.

Versioned like the reference's BPAPI (proto/*_proto_vN modules +
emqx_bpapi static checks): the hello handshake carries PROTO_VER and a
node refuses peers with an incompatible major version.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from .. import failpoints

log = logging.getLogger("emqx_tpu.cluster.transport")

PROTO_VER = (3, 0)

# a handler returning this sentinel suppresses the reply frame even
# for a call: the caller consumes its full RPC timeout, exactly like a
# reply the network lost (the raft failpoint seam relies on it)
NO_REPLY = object()

Handler = Callable[[str, Dict[str, Any]], Awaitable[Optional[Dict[str, Any]]]]


def pack_bytes(b: bytes) -> str:
    return base64.b64encode(b).decode("ascii")


def unpack_bytes(s: str) -> bytes:
    return base64.b64decode(s.encode("ascii"))


_F_JSON = 0
_F_BIN = 1


def _pack_json(obj: Dict[str, Any]) -> bytes:
    data = json.dumps(obj, separators=(",", ":")).encode()
    return (len(data) + 1).to_bytes(4, "big") + bytes([_F_JSON]) + data


def _pack_bin(mtype: str, payload: bytes) -> bytes:
    t = mtype.encode()
    body_len = 1 + 1 + len(t) + len(payload)
    return (
        body_len.to_bytes(4, "big")
        + bytes([_F_BIN])
        + bytes([len(t)])
        + t
        + payload
    )


class PeerLink:
    """One outgoing connection to a peer, with lazy (re)connect and
    per-peer ordered sends."""

    def __init__(
        self,
        self_node: str,
        addr: Tuple[str, int],
        connect_timeout: float = 2.0,
    ) -> None:
        self.self_node = self_node
        self.addr = addr
        self.connect_timeout = connect_timeout
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._lock = asyncio.Lock()
        self._calls: Dict[int, asyncio.Future] = {}
        self._call_seq = 0
        self._pump: Optional[asyncio.Task] = None

    async def _ensure(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        # bounded connect: a blackholed peer must fail fast, not hang
        # the caller for the kernel SYN timeout
        try:
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(*self.addr), self.connect_timeout
            )
        except asyncio.TimeoutError as exc:
            raise ConnectionError(f"connect to {self.addr} timed out") from exc
        await self._send_obj(
            {"type": "hello", "node": self.self_node, "ver": list(PROTO_VER)}
        )
        self._pump = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while self._reader is not None:
                obj = await read_frame(self._reader)
                if obj is None:
                    break
                if obj.get("type") == "reply":
                    fut = self._calls.pop(obj.get("call_id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(obj.get("result"))
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            for fut in self._calls.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("peer link lost"))
            self._calls.clear()

    async def _send_obj(self, obj: Dict[str, Any]) -> None:
        assert self._writer is not None
        self._writer.write(_pack_json(obj))
        await self._writer.drain()

    async def cast_bin(self, mtype: str, payload: bytes) -> bool:
        """Fire-and-forget binary frame: payload bytes travel raw (no
        JSON/base64 re-encode — the message-forward hot path)."""
        # per-peer FIFO + backpressure: holding the lock across
        # connect/write/drain IS the design — it caps buffered bytes
        # at one frame over the high-water mark per peer, and send
        # order is the route-op stream's consistency guarantee
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._writer.write(_pack_bin(mtype, payload))
                await self._writer.drain()
                return True
            except (ConnectionError, OSError):
                self._drop()
                return False

    async def cast(self, obj: Dict[str, Any]) -> bool:
        """Fire-and-forget; returns False when the peer is unreachable
        (the caller decides whether that matters — async forward mode,
        emqx_broker.erl:387-391 forward_async)."""
        # same per-peer FIFO/backpressure rationale as cast_bin
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                await self._send_obj(obj)
                return True
            except (ConnectionError, OSError):
                self._drop()
                return False

    async def call(
        self, obj: Dict[str, Any], timeout: float = 5.0
    ) -> Optional[Dict[str, Any]]:
        # lock covers connect+register+write only — the reply is
        # awaited OUTSIDE it, so slow calls don't serialize; the
        # remaining IO under the lock is the FIFO/backpressure bound
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._call_seq += 1
                cid = self._call_seq
                obj = dict(obj, call_id=cid)
                fut: asyncio.Future = (
                    asyncio.get_running_loop().create_future()
                )
                self._calls[cid] = fut
                await self._send_obj(obj)
            except (ConnectionError, OSError):
                self._drop()
                return None
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, ConnectionError):
            return None

    def _drop(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
            self._pump = None
        if self._writer is not None:
            self._writer.close()
        self._writer = None
        self._reader = None

    def close(self) -> None:
        self._drop()


MAX_FRAME = 64 * 1024 * 1024


def parse_frame(data: bytes) -> Dict:
    """Parse one frame body (the bytes after the length prefix).

    EVERY malformed-frame failure normalizes to ``ConnectionError`` —
    a peer feeding garbage (zero-length body, truncated binary header,
    undecodable type, broken JSON) is treated exactly like a peer that
    dropped the connection: the serve loop survives and resets the
    link instead of crashing on a stray IndexError/UnicodeDecodeError.
    """
    try:
        fmt = data[0]
        if fmt == _F_JSON:
            obj = json.loads(data[1:])
            if not isinstance(obj, dict):
                raise ConnectionError("non-object JSON cluster frame")
            return obj
        if fmt == _F_BIN:
            tlen = data[1]
            if 2 + tlen > len(data):
                raise ConnectionError(
                    "truncated binary cluster frame header"
                )
            mtype = data[2 : 2 + tlen].decode()
            return {"type": mtype, "_bin": data[2 + tlen :]}
        raise ConnectionError(f"unknown frame format {fmt}")
    except ConnectionError:
        raise
    except (IndexError, UnicodeDecodeError, ValueError) as exc:
        # IndexError: empty/short body; UnicodeDecodeError: bad type
        # bytes; ValueError covers json.JSONDecodeError
        raise ConnectionError(f"malformed cluster frame: {exc}") from exc


def drain_frames(buf: bytearray) -> List[Dict]:
    """Pop every complete length-prefixed frame from ``buf`` (a
    stream-reassembly buffer — the QUIC peer transport's stream
    deframer).  Raises ConnectionError on oversized/malformed frames,
    mutating ``buf`` in place."""
    out: List[Dict] = []
    while len(buf) >= 4:
        n = int.from_bytes(buf[:4], "big")
        if n > MAX_FRAME:
            raise ConnectionError(f"oversized cluster frame: {n}")
        if len(buf) < 4 + n:
            break
        body = bytes(buf[4 : 4 + n])
        del buf[: 4 + n]
        out.append(parse_frame(body))
    return out


async def read_frame(reader: asyncio.StreamReader) -> Optional[Dict]:
    """Read one frame.  Format 0 = JSON control message; format 1 =
    binary: returned as {"type": mtype, "_bin": payload-bytes}."""
    try:
        head = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    n = int.from_bytes(head, "big")
    if n > MAX_FRAME:
        raise ConnectionError(f"oversized cluster frame: {n}")
    try:
        data = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return parse_frame(data)


class NodeTransport:
    """The node's RPC endpoint: a listening server plus peer links."""

    def __init__(self, node: str, bind: str = "127.0.0.1", port: int = 0,
                 transport_mode: str = "tcp",
                 quic_psk: Optional[bytes] = None,
                 quic_reprobe_interval: float = 5.0):
        if transport_mode not in ("tcp", "quic", "auto"):
            raise ValueError(
                f"transport_mode must be tcp|quic|auto, "
                f"got {transport_mode!r}"
            )
        self.node = node
        self.bind = bind
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._links: Dict[str, PeerLink] = {}
        self._handlers: Dict[str, Handler] = {}
        self._concurrent: set = set()  # handlers that run as tasks
        self._peer_addrs: Dict[str, Tuple[str, int]] = {}
        self._inbound: set = set()  # live inbound connection writers
        self._tasks: set = set()
        # fault-injection surface (partition tests, tp.py philosophy):
        # outbound traffic to a blocked peer is dropped as if the
        # network ate it — both sides blocking = a full partition
        self.blocked: set = set()
        # QUIC peer transport (cluster/quic_transport.py): the UDP
        # endpoint binds the SAME port number as the TCP listener, so
        # membership carries one (host, port) per peer for both.
        # "auto" prefers QUIC and degrades per peer to the TCP
        # PeerLink on handshake failure, re-probing QUIC after
        # `quic_reprobe_interval` seconds.
        self.transport_mode = transport_mode
        self.quic_psk = quic_psk
        self.quic_reprobe_interval = quic_reprobe_interval
        self.quic_connect_timeout = 1.0  # hello/hello_ack deadline
        self.quic_endpoint = None  # QuicPeerEndpoint when mode != tcp
        self._qlinks: Dict[str, Any] = {}  # QuicPeerLink per peer
        self._quic_retry_at: Dict[str, float] = {}  # auto re-probe time
        self._quic_probing: set = set()  # peers with a probe in flight
        self.stats = {"quic_demotions": 0, "quic_promotions": 0,
                      "quic_sends": 0, "tcp_sends": 0}

    def on(self, mtype: str, handler: Handler,
           concurrent: bool = False) -> None:
        """Register a handler.  ``concurrent=True`` runs each request
        as its own task (reply sent when it finishes) instead of
        inline in the connection's serial read loop — REQUIRED for
        handlers that await quorum traffic arriving on the same
        connection (forward_sync awaiting a raft commit whose
        AppendEntries share the link would deadlock otherwise).
        Serial handlers keep per-peer FIFO (route-op streams)."""
        self._handlers[mtype] = handler
        if concurrent:
            self._concurrent.add(mtype)
        else:
            self._concurrent.discard(mtype)

    def add_peer(self, node: str, host: str, port: int) -> None:
        self._peer_addrs[node] = (host, port)

    def drop_peer(self, node: str) -> None:
        link = self._links.pop(node, None)
        if link is not None:
            link.close()
        qlink = self._qlinks.pop(node, None)
        if qlink is not None:
            qlink.close()
        self._quic_retry_at.pop(node, None)

    async def start(self) -> None:
        if self._server is not None:
            return  # idempotent: callers may pre-start to learn the port
        self._server = await asyncio.start_server(
            self._on_conn, self.bind, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.transport_mode in ("quic", "auto"):
            from .quic_transport import QuicPeerEndpoint

            endpoint = QuicPeerEndpoint(
                self, self.bind, self.port, psk=self.quic_psk or b""
            )
            try:
                await endpoint.start()
                self.quic_endpoint = endpoint
            except OSError:
                if self.transport_mode == "quic":
                    raise
                # auto: this node serves TCP only; its QUIC dials to
                # peers still work (outbound needs no local bind)
                log.warning(
                    "transport %s: QUIC UDP bind failed; serving "
                    "TCP only", self.node, exc_info=True,
                )

    async def stop(self) -> None:
        # close OUR ends first: Python 3.12's Server.wait_closed()
        # waits for every live connection handler, and peers' idle
        # inbound links would otherwise hold it open forever
        for link in self._links.values():
            link.close()
        self._links.clear()
        for qlink in self._qlinks.values():
            qlink.close()
        self._qlinks.clear()
        # probe tasks dial on their own clock; reap them so a stopping
        # node cannot leak a dial into a closing event loop
        for task in list(self._tasks):
            task.cancel()
        self._tasks.clear()
        if self.quic_endpoint is not None:
            await self.quic_endpoint.stop()
            self.quic_endpoint = None
        for w in list(self._inbound):
            w.close()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                log.warning("transport %s: wait_closed timed out",
                            self.node)
            self._server = None

    def _link(self, node: str) -> Optional[PeerLink]:
        link = self._links.get(node)
        if link is None:
            addr = self._peer_addrs.get(node)
            if addr is None:
                return None
            link = self._links[node] = PeerLink(self.node, addr)
        return link

    def _qlink(self, node: str):
        link = self._qlinks.get(node)
        if link is not None and link.degraded:
            # a degraded link object fails fast forever (by design —
            # waiters queued behind the failed dial must not each pay
            # the timeout); hard "quic" mode has no demotion path to
            # replace it, so replace it HERE: the next send redials
            link.close()
            self._qlinks.pop(node, None)
            link = None
        if link is None:
            addr = self._peer_addrs.get(node)
            if addr is None:
                return None
            from .quic_transport import QuicPeerLink

            link = self._qlinks[node] = QuicPeerLink(
                self.node, node, addr, psk=self.quic_psk or b"",
                connect_timeout=self.quic_connect_timeout,
            )
        return link

    def _route(self, node: str) -> Tuple[Optional[Any], bool]:
        """Pick the active link for ``node``: ``(link, is_quic)``.

        tcp  -> the TCP PeerLink, always.
        quic -> the QUIC link, always (hard mode: no silent fallback).
        auto -> QUIC, unless this peer is demoted (handshake failure/
                link fault).  A demoted peer's traffic stays on TCP —
                after `quic_reprobe_interval` a BACKGROUND probe
                re-dials QUIC and re-promotes on success, so re-probes
                never stall live casts (a heartbeat bounded tighter
                than the handshake timeout must not get eaten by an
                in-band dial)."""
        if self.transport_mode == "tcp":
            return self._link(node), False
        if self.transport_mode == "quic":
            return self._qlink(node), True
        if node not in self._quic_retry_at:
            return self._qlink(node), True
        import time

        if time.monotonic() >= self._quic_retry_at[node]:
            self._kick_quic_probe(node)
        return self._link(node), False

    def _demote_quic(self, node: str) -> None:
        """auto mode: park this peer on TCP and schedule a QUIC
        re-probe (the link object is dropped so the probe redials)."""
        link = self._qlinks.pop(node, None)
        if link is not None:
            link.close()
        import time

        already = node in self._quic_retry_at
        self._quic_retry_at[node] = (
            time.monotonic() + self.quic_reprobe_interval
        )
        if not already:
            self.stats["quic_demotions"] += 1
            log.info(
                "transport %s: peer %s demoted to TCP (QUIC re-probe "
                "in %.1fs)", self.node, node,
                self.quic_reprobe_interval,
            )

    def _kick_quic_probe(self, node: str) -> None:
        if node in self._quic_probing:
            return
        self._quic_probing.add(node)
        task = asyncio.get_running_loop().create_task(
            self._quic_probe(node)
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _quic_probe(self, node: str) -> None:
        """Background QUIC re-promotion probe: dial + handshake on a
        FRESH link while the peer's traffic keeps flowing over TCP;
        success swaps the link in and clears the demotion."""
        import time

        try:
            addr = self._peer_addrs.get(node)
            if addr is None:
                return
            from .quic_transport import QuicPeerLink

            link = QuicPeerLink(
                self.node, node, addr, psk=self.quic_psk or b"",
                connect_timeout=self.quic_connect_timeout,
            )
            try:
                await link.probe()
            except (ConnectionError, OSError):
                link.close()
                self._quic_retry_at[node] = (
                    time.monotonic() + self.quic_reprobe_interval
                )
                return
            old = self._qlinks.pop(node, None)
            if old is not None:
                old.close()
            self._qlinks[node] = link
            self._quic_retry_at.pop(node, None)
            self.stats["quic_promotions"] += 1
            log.info("transport %s: peer %s re-promoted to QUIC",
                     self.node, node)
        finally:
            self._quic_probing.discard(node)

    async def _send_failpoint(self, node: str) -> Optional[str]:
        """Chaos seam for every outbound frame to `node`.  ``drop``
        swallows the frame as if the network ate it, ``duplicate``
        asks the caller to send twice, ``delay`` adds link latency
        inline, ``error`` raises `FailpointError` (a ConnectionError —
        the detected-failure path).  Keyed ``self->peer`` so a
        ``match`` substring can partition one node in both
        directions."""
        return await failpoints.evaluate_async(
            "cluster.transport.send", key=f"{self.node}->{node}"
        )

    async def _cast_routed(self, node: str, kind: str, obj, mtype: str,
                           payload) -> bool:
        """One cast over the routed link; ``auto`` retries ONCE over
        TCP after demoting a failed QUIC link, so a degrading peer
        loses no frame on the transition."""
        link, is_quic = self._route(node)
        if link is None:
            return False
        ok = await (
            link.cast(obj) if kind == "cast"
            else link.cast_bin(mtype, payload)
        )
        if not ok and is_quic and self.transport_mode == "auto":
            self._demote_quic(node)
            link = self._link(node)
            if link is not None:
                ok = await (
                    link.cast(obj) if kind == "cast"
                    else link.cast_bin(mtype, payload)
                )
                is_quic = False  # the frame that went out went on TCP
        if ok:
            self.stats["quic_sends" if is_quic else "tcp_sends"] += 1
        return ok

    async def cast(self, node: str, obj: Dict[str, Any]) -> bool:
        if node in self.blocked:
            return False
        if failpoints.enabled:
            try:
                act = await self._send_failpoint(node)
            except failpoints.FailpointError:
                return False
            if act == "drop":
                return True  # silent loss: the sender believes it went
            if act == "duplicate":
                await self._cast_routed(node, "cast", obj, "", b"")
        return await self._cast_routed(node, "cast", obj, "", b"")

    async def cast_bin(self, node: str, mtype: str, payload: bytes) -> bool:
        if node in self.blocked:
            return False
        if failpoints.enabled:
            try:
                act = await self._send_failpoint(node)
            except failpoints.FailpointError:
                return False
            if act == "drop":
                return True
            if act == "duplicate":
                await self._cast_routed(node, "bin", None, mtype, payload)
        return await self._cast_routed(node, "bin", None, mtype, payload)

    async def call(
        self, node: str, obj: Dict[str, Any], timeout: float = 5.0
    ) -> Optional[Dict[str, Any]]:
        if node in self.blocked:
            return None
        if failpoints.enabled:
            try:
                act = await self._send_failpoint(node)
            except failpoints.FailpointError:
                return None
            if act == "drop":
                return None  # the reply will never come
        link, is_quic = self._route(node)
        if link is None:
            return None
        result = await link.call(obj, timeout)
        if result is None and is_quic and self.transport_mode == "auto" \
                and getattr(link, "degraded", False):
            # only a DEAD QUIC link falls back (handshake/link fault);
            # a timed-out reply over a healthy link must not re-issue
            # the call on TCP — the peer may have executed it already
            self._demote_quic(node)
            tlink = self._link(node)
            if tlink is not None:
                result = await tlink.call(obj, timeout)
                is_quic = False
        if result is not None:
            self.stats["quic_sends" if is_quic else "tcp_sends"] += 1
        return result

    async def _dispatch_frame(
        self, peer: str, obj: Dict[str, Any], writer
    ) -> None:
        """Route one inbound frame to its handler and send the reply
        (shared by the TCP serve loop and the QUIC endpoint's
        per-connection pumps; ``writer`` only needs write/drain/
        is_closing).  Serial handlers run inline — the CALLER's pump
        is the per-peer FIFO; concurrent handlers spawn."""
        mtype = obj.get("type", "")
        handler = self._handlers.get(mtype)
        if handler is None:
            log.warning("no handler for %r from %s", mtype, peer)
            return
        if mtype in self._concurrent:
            task = asyncio.get_running_loop().create_task(
                self._handle_and_reply(handler, peer, obj, writer)
            )
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            return
        result = await handler(peer, obj)
        if "call_id" in obj and result is not NO_REPLY:
            writer.write(_pack_json({
                "type": "reply",
                "call_id": obj["call_id"],
                "result": result,
            }))
            await writer.drain()

    async def _handle_and_reply(
        self, handler: Handler, peer: str, obj: Dict[str, Any],
        writer: asyncio.StreamWriter,
    ) -> None:
        try:
            result = await handler(peer, obj)
            if result is NO_REPLY:
                return
            if "call_id" in obj and not writer.is_closing():
                writer.write(_pack_json({
                    "type": "reply",
                    "call_id": obj["call_id"],
                    "result": result,
                }))
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        except Exception:
            log.exception("concurrent handler %r from %s crashed",
                          obj.get("type"), peer)

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = "?"
        self._inbound.add(writer)
        try:
            hello = await read_frame(reader)
            if not hello or hello.get("type") != "hello":
                return
            ver = tuple(hello.get("ver", ()))
            if not ver or ver[0] != PROTO_VER[0]:
                log.warning(
                    "rejecting peer %s: proto %s != %s",
                    hello.get("node"),
                    ver,
                    PROTO_VER,
                )
                return
            peer = hello.get("node", "?")
            while True:
                obj = await read_frame(reader)
                if obj is None:
                    return
                if failpoints.enabled:
                    # inbound chaos seam: drop loses the frame after
                    # the wire delivered it; error (ConnectionError)
                    # resets the inbound link like a real peer fault
                    act = await failpoints.evaluate_async(
                        "cluster.transport.recv",
                        key=f"{peer}->{self.node}",
                    )
                    if act == "drop":
                        continue
                await self._dispatch_frame(peer, obj, writer)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.exception("cluster connection from %s crashed", peer)
        finally:
            self._inbound.discard(writer)
            writer.close()
