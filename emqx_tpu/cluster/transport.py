"""Inter-node RPC transport: length-prefixed JSON over asyncio TCP.

The gen_rpc analogue (/root/reference/apps/emqx/src/emqx_rpc.erl:82-119
wraps gen_rpc casts/calls): one listening server per node, one outgoing
connection per peer, messages are JSON objects with a ``type`` field
dispatched to registered handlers.  Casts are fire-and-forget (ordered
per peer, like gen_rpc's per-key ordered casts); calls carry a
``call_id`` and await a ``reply``.

Versioned like the reference's BPAPI (proto/*_proto_vN modules +
emqx_bpapi static checks): the hello handshake carries PROTO_VER and a
node refuses peers with an incompatible major version.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from .. import failpoints

log = logging.getLogger("emqx_tpu.cluster.transport")

PROTO_VER = (3, 0)

# a handler returning this sentinel suppresses the reply frame even
# for a call: the caller consumes its full RPC timeout, exactly like a
# reply the network lost (the raft failpoint seam relies on it)
NO_REPLY = object()

Handler = Callable[[str, Dict[str, Any]], Awaitable[Optional[Dict[str, Any]]]]


def pack_bytes(b: bytes) -> str:
    return base64.b64encode(b).decode("ascii")


def unpack_bytes(s: str) -> bytes:
    return base64.b64decode(s.encode("ascii"))


_F_JSON = 0
_F_BIN = 1


def _pack_json(obj: Dict[str, Any]) -> bytes:
    data = json.dumps(obj, separators=(",", ":")).encode()
    return (len(data) + 1).to_bytes(4, "big") + bytes([_F_JSON]) + data


def _pack_bin(mtype: str, payload: bytes) -> bytes:
    t = mtype.encode()
    body_len = 1 + 1 + len(t) + len(payload)
    return (
        body_len.to_bytes(4, "big")
        + bytes([_F_BIN])
        + bytes([len(t)])
        + t
        + payload
    )


class PeerLink:
    """One outgoing connection to a peer, with lazy (re)connect and
    per-peer ordered sends."""

    def __init__(
        self,
        self_node: str,
        addr: Tuple[str, int],
        connect_timeout: float = 2.0,
    ) -> None:
        self.self_node = self_node
        self.addr = addr
        self.connect_timeout = connect_timeout
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._lock = asyncio.Lock()
        self._calls: Dict[int, asyncio.Future] = {}
        self._call_seq = 0
        self._pump: Optional[asyncio.Task] = None

    async def _ensure(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        # bounded connect: a blackholed peer must fail fast, not hang
        # the caller for the kernel SYN timeout
        try:
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(*self.addr), self.connect_timeout
            )
        except asyncio.TimeoutError as exc:
            raise ConnectionError(f"connect to {self.addr} timed out") from exc
        await self._send_obj(
            {"type": "hello", "node": self.self_node, "ver": list(PROTO_VER)}
        )
        self._pump = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while self._reader is not None:
                obj = await read_frame(self._reader)
                if obj is None:
                    break
                if obj.get("type") == "reply":
                    fut = self._calls.pop(obj.get("call_id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(obj.get("result"))
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            for fut in self._calls.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("peer link lost"))
            self._calls.clear()

    async def _send_obj(self, obj: Dict[str, Any]) -> None:
        assert self._writer is not None
        self._writer.write(_pack_json(obj))
        await self._writer.drain()

    async def cast_bin(self, mtype: str, payload: bytes) -> bool:
        """Fire-and-forget binary frame: payload bytes travel raw (no
        JSON/base64 re-encode — the message-forward hot path)."""
        # per-peer FIFO + backpressure: holding the lock across
        # connect/write/drain IS the design — it caps buffered bytes
        # at one frame over the high-water mark per peer, and send
        # order is the route-op stream's consistency guarantee
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._writer.write(_pack_bin(mtype, payload))
                await self._writer.drain()
                return True
            except (ConnectionError, OSError):
                self._drop()
                return False

    async def cast(self, obj: Dict[str, Any]) -> bool:
        """Fire-and-forget; returns False when the peer is unreachable
        (the caller decides whether that matters — async forward mode,
        emqx_broker.erl:387-391 forward_async)."""
        # same per-peer FIFO/backpressure rationale as cast_bin
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                await self._send_obj(obj)
                return True
            except (ConnectionError, OSError):
                self._drop()
                return False

    async def call(
        self, obj: Dict[str, Any], timeout: float = 5.0
    ) -> Optional[Dict[str, Any]]:
        # lock covers connect+register+write only — the reply is
        # awaited OUTSIDE it, so slow calls don't serialize; the
        # remaining IO under the lock is the FIFO/backpressure bound
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._call_seq += 1
                cid = self._call_seq
                obj = dict(obj, call_id=cid)
                fut: asyncio.Future = (
                    asyncio.get_running_loop().create_future()
                )
                self._calls[cid] = fut
                await self._send_obj(obj)
            except (ConnectionError, OSError):
                self._drop()
                return None
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, ConnectionError):
            return None

    def _drop(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
            self._pump = None
        if self._writer is not None:
            self._writer.close()
        self._writer = None
        self._reader = None

    def close(self) -> None:
        self._drop()


async def read_frame(reader: asyncio.StreamReader) -> Optional[Dict]:
    """Read one frame.  Format 0 = JSON control message; format 1 =
    binary: returned as {"type": mtype, "_bin": payload-bytes}."""
    try:
        head = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    n = int.from_bytes(head, "big")
    if n > 64 * 1024 * 1024:
        raise ConnectionError(f"oversized cluster frame: {n}")
    try:
        data = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    fmt = data[0]
    if fmt == _F_JSON:
        return json.loads(data[1:])
    if fmt == _F_BIN:
        tlen = data[1]
        mtype = data[2 : 2 + tlen].decode()
        return {"type": mtype, "_bin": data[2 + tlen :]}
    raise ConnectionError(f"unknown frame format {fmt}")


class NodeTransport:
    """The node's RPC endpoint: a listening server plus peer links."""

    def __init__(self, node: str, bind: str = "127.0.0.1", port: int = 0):
        self.node = node
        self.bind = bind
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._links: Dict[str, PeerLink] = {}
        self._handlers: Dict[str, Handler] = {}
        self._concurrent: set = set()  # handlers that run as tasks
        self._peer_addrs: Dict[str, Tuple[str, int]] = {}
        self._inbound: set = set()  # live inbound connection writers
        self._tasks: set = set()
        # fault-injection surface (partition tests, tp.py philosophy):
        # outbound traffic to a blocked peer is dropped as if the
        # network ate it — both sides blocking = a full partition
        self.blocked: set = set()

    def on(self, mtype: str, handler: Handler,
           concurrent: bool = False) -> None:
        """Register a handler.  ``concurrent=True`` runs each request
        as its own task (reply sent when it finishes) instead of
        inline in the connection's serial read loop — REQUIRED for
        handlers that await quorum traffic arriving on the same
        connection (forward_sync awaiting a raft commit whose
        AppendEntries share the link would deadlock otherwise).
        Serial handlers keep per-peer FIFO (route-op streams)."""
        self._handlers[mtype] = handler
        if concurrent:
            self._concurrent.add(mtype)
        else:
            self._concurrent.discard(mtype)

    def add_peer(self, node: str, host: str, port: int) -> None:
        self._peer_addrs[node] = (host, port)

    def drop_peer(self, node: str) -> None:
        link = self._links.pop(node, None)
        if link is not None:
            link.close()

    async def start(self) -> None:
        if self._server is not None:
            return  # idempotent: callers may pre-start to learn the port
        self._server = await asyncio.start_server(
            self._on_conn, self.bind, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        # close OUR ends first: Python 3.12's Server.wait_closed()
        # waits for every live connection handler, and peers' idle
        # inbound links would otherwise hold it open forever
        for link in self._links.values():
            link.close()
        self._links.clear()
        for w in list(self._inbound):
            w.close()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                log.warning("transport %s: wait_closed timed out",
                            self.node)
            self._server = None

    def _link(self, node: str) -> Optional[PeerLink]:
        link = self._links.get(node)
        if link is None:
            addr = self._peer_addrs.get(node)
            if addr is None:
                return None
            link = self._links[node] = PeerLink(self.node, addr)
        return link

    async def _send_failpoint(self, node: str) -> Optional[str]:
        """Chaos seam for every outbound frame to `node`.  ``drop``
        swallows the frame as if the network ate it, ``duplicate``
        asks the caller to send twice, ``delay`` adds link latency
        inline, ``error`` raises `FailpointError` (a ConnectionError —
        the detected-failure path).  Keyed ``self->peer`` so a
        ``match`` substring can partition one node in both
        directions."""
        return await failpoints.evaluate_async(
            "cluster.transport.send", key=f"{self.node}->{node}"
        )

    async def cast(self, node: str, obj: Dict[str, Any]) -> bool:
        if node in self.blocked:
            return False
        if failpoints.enabled:
            try:
                act = await self._send_failpoint(node)
            except failpoints.FailpointError:
                return False
            if act == "drop":
                return True  # silent loss: the sender believes it went
            if act == "duplicate":
                link = self._link(node)
                if link is not None:
                    await link.cast(obj)
        link = self._link(node)
        return False if link is None else await link.cast(obj)

    async def cast_bin(self, node: str, mtype: str, payload: bytes) -> bool:
        if node in self.blocked:
            return False
        if failpoints.enabled:
            try:
                act = await self._send_failpoint(node)
            except failpoints.FailpointError:
                return False
            if act == "drop":
                return True
            if act == "duplicate":
                link = self._link(node)
                if link is not None:
                    await link.cast_bin(mtype, payload)
        link = self._link(node)
        return False if link is None else await link.cast_bin(mtype, payload)

    async def call(
        self, node: str, obj: Dict[str, Any], timeout: float = 5.0
    ) -> Optional[Dict[str, Any]]:
        if node in self.blocked:
            return None
        if failpoints.enabled:
            try:
                act = await self._send_failpoint(node)
            except failpoints.FailpointError:
                return None
            if act == "drop":
                return None  # the reply will never come
        link = self._link(node)
        return None if link is None else await link.call(obj, timeout)

    async def _handle_and_reply(
        self, handler: Handler, peer: str, obj: Dict[str, Any],
        writer: asyncio.StreamWriter,
    ) -> None:
        try:
            result = await handler(peer, obj)
            if result is NO_REPLY:
                return
            if "call_id" in obj and not writer.is_closing():
                writer.write(_pack_json({
                    "type": "reply",
                    "call_id": obj["call_id"],
                    "result": result,
                }))
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        except Exception:
            log.exception("concurrent handler %r from %s crashed",
                          obj.get("type"), peer)

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = "?"
        self._inbound.add(writer)
        try:
            hello = await read_frame(reader)
            if not hello or hello.get("type") != "hello":
                return
            ver = tuple(hello.get("ver", ()))
            if not ver or ver[0] != PROTO_VER[0]:
                log.warning(
                    "rejecting peer %s: proto %s != %s",
                    hello.get("node"),
                    ver,
                    PROTO_VER,
                )
                return
            peer = hello.get("node", "?")
            while True:
                obj = await read_frame(reader)
                if obj is None:
                    return
                if failpoints.enabled:
                    # inbound chaos seam: drop loses the frame after
                    # the wire delivered it; error (ConnectionError)
                    # resets the inbound link like a real peer fault
                    act = await failpoints.evaluate_async(
                        "cluster.transport.recv",
                        key=f"{peer}->{self.node}",
                    )
                    if act == "drop":
                        continue
                mtype = obj.get("type", "")
                handler = self._handlers.get(mtype)
                if handler is None:
                    log.warning("no handler for %r from %s", mtype, peer)
                    continue
                if mtype in self._concurrent:
                    task = asyncio.get_running_loop().create_task(
                        self._handle_and_reply(handler, peer, obj, writer)
                    )
                    self._tasks.add(task)
                    task.add_done_callback(self._tasks.discard)
                    continue
                result = await handler(peer, obj)
                if "call_id" in obj and result is not NO_REPLY:
                    writer.write(
                        _pack_json(
                            {
                                "type": "reply",
                                "call_id": obj["call_id"],
                                "result": result,
                            }
                        )
                    )
                    await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.exception("cluster connection from %s crashed", peer)
        finally:
            self._inbound.discard(writer)
            writer.close()
