"""Binary wire codec for forwarded message batches.

The cluster hot path previously re-encoded every forwarded payload as
base64 inside a JSON cast — triple-copying bytes and one cast per
message (VERDICT r2 weak #7).  Batches now pack with a fixed binary
layout (payload bytes raw), mirroring how gen_rpc ships Erlang terms
without re-encoding (emqx_rpc.erl:82-119 transport role).

Layout per message (big-endian):
  u16 topic_len | topic utf8
  u8  flags      (bit0-1 qos, bit2 retain, bit3 sys, bit4 dup,
                  bit5 has_username)
  u16 from_len   | from_client utf8
  [u16 user_len  | username utf8]        when has_username
  u8  mid_len    | mid bytes
  f64 timestamp
  u32 props_len  | properties JSON utf8  (rare, usually b"{}")
  u32 payload_len| payload bytes
"""

from __future__ import annotations

import json
import struct
from typing import List

from ..message import Message


def _props_default(o):
    if isinstance(o, (bytes, bytearray)):
        return {"$b": o.hex()}
    raise TypeError(str(type(o)))


def _props_hook(d):
    if set(d) == {"$b"}:
        return bytes.fromhex(d["$b"])
    return d


def encode_messages(msgs: List[Message]) -> bytes:
    out = bytearray()
    out += struct.pack(">I", len(msgs))
    for m in msgs:
        topic = m.topic.encode()
        frm = (m.from_client or "").encode()
        user = m.from_username.encode() if m.from_username else None
        props = (
            json.dumps(
                m.properties, separators=(",", ":"), default=_props_default
            ).encode()
            if m.properties
            else b"{}"
        )
        flags = (
            (m.qos & 3)
            | (0x04 if m.retain else 0)
            | (0x08 if m.sys else 0)
            | (0x10 if m.dup else 0)
            | (0x20 if user is not None else 0)
        )
        out += struct.pack(">H", len(topic)) + topic
        out += bytes([flags])
        out += struct.pack(">H", len(frm)) + frm
        if user is not None:
            out += struct.pack(">H", len(user)) + user
        out += bytes([len(m.mid)]) + m.mid
        out += struct.pack(">d", m.timestamp)
        out += struct.pack(">I", len(props)) + props
        out += struct.pack(">I", len(m.payload)) + m.payload
    return bytes(out)


# --------------------------------------------- sequenced window frames

# header layout (big-endian), before the encode_messages body:
#   u64 epoch  — the origin's process incarnation (ClusterNode._epoch
#                truncated to 64 bits); a restart starts a new frame
#                stream, so the receiver resets its dedup window
#   u64 seq    — per-(origin, peer) frame sequence number, from 1
#   u64 base   — the LOWEST seq the origin still holds unacked: every
#                frame below it was acked or shed, so the receiver can
#                advance its dedup floor past holes that overflow
#                shedding punched into the stream
#   u8  flags  — bit0-1: max QoS carried by the frame's messages
_WINDOW_HDR = struct.Struct(">QQQB")


def encode_window(epoch: int, seq: int, base: int,
                  msgs: List[Message]) -> bytes:
    """One at-least-once forward frame: reliability header + the
    batched message body (`encode_messages`)."""
    max_qos = 0
    for m in msgs:
        if m.qos > max_qos:
            max_qos = m.qos
            if max_qos >= 2:
                break
    return (
        _WINDOW_HDR.pack(epoch & (2**64 - 1), seq, base, max_qos & 3)
        + encode_messages(msgs)
    )


def decode_window(data: bytes):
    """-> (epoch, seq, base, max_qos, msgs).  Malformed frames raise
    (the caller's serve loop logs and drops them)."""
    epoch, seq, base, flags = _WINDOW_HDR.unpack_from(data, 0)
    msgs = decode_messages(data[_WINDOW_HDR.size:])
    return epoch, seq, base, flags & 3, msgs


def decode_messages(data: bytes) -> List[Message]:
    view = memoryview(data)
    (n,) = struct.unpack_from(">I", view, 0)
    off = 4
    out: List[Message] = []
    for _ in range(n):
        (tlen,) = struct.unpack_from(">H", view, off)
        off += 2
        topic = bytes(view[off : off + tlen]).decode()
        off += tlen
        flags = view[off]
        off += 1
        (flen,) = struct.unpack_from(">H", view, off)
        off += 2
        frm = bytes(view[off : off + flen]).decode()
        off += flen
        user = None
        if flags & 0x20:
            (ulen,) = struct.unpack_from(">H", view, off)
            off += 2
            user = bytes(view[off : off + ulen]).decode()
            off += ulen
        mlen = view[off]
        off += 1
        mid = bytes(view[off : off + mlen])
        off += mlen
        (ts,) = struct.unpack_from(">d", view, off)
        off += 8
        (plen,) = struct.unpack_from(">I", view, off)
        off += 4
        props = json.loads(
            bytes(view[off : off + plen]).decode(), object_hook=_props_hook
        )
        off += plen
        (blen,) = struct.unpack_from(">I", view, off)
        off += 4
        payload = bytes(view[off : off + blen])
        off += blen
        out.append(
            Message(
                topic=topic,
                payload=payload,
                qos=flags & 3,
                retain=bool(flags & 0x04),
                sys=bool(flags & 0x08),
                dup=bool(flags & 0x10),
                from_client=frm,
                from_username=user,
                mid=mid,
                timestamp=ts,
                properties=props,
            )
        )
    return out
