"""ClusterNode: wires one Broker into a cluster of peers.

Re-creates the reference's cluster spine on asyncio + the shared match
engine:

  * route-delta broadcast with batching — `emqx_router_syncer` batches
    ops into single mria txns (/root/reference/apps/emqx/src/
    emqx_router_syncer.erl:58,115-121); here local route add/del ops
    buffer briefly and flush as one ``route_ops`` cast to every peer.
  * publish forwarding — `emqx_broker:forward/4` async mode via
    gen_rpc (emqx_broker.erl:387-406); here a ``forward`` cast carrying
    the message to each node whose replica matches the topic.
  * membership — ekka-style static seeds + heartbeats; a node missing
    heartbeats past the timeout is declared down and its routes are
    purged from the local replica (`emqx_router_helper` dead-node
    cleanup, emqx_router.erl:316-323).  A node heard from again is
    re-synced with a full route exchange.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import random
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from .. import failpoints
from ..aio import cancel_and_wait
from ..flightrec import EV_FWD as _EV_FWD
from ..ds.replication import ReplicaStore, rendezvous_pick
from ..message import Message
from .routes import ClusterRouteTable
from .transport import NodeTransport, pack_bytes, unpack_bytes

log = logging.getLogger("emqx_tpu.cluster")


class _FwdFrame:
    """One sequenced forward window held until the peer acks it."""

    __slots__ = ("seq", "blob", "n", "max_qos", "spans", "sent_at",
                 "retx")

    def __init__(self, seq: int, blob: bytes, n: int, max_qos: int,
                 spans) -> None:
        self.seq = seq
        self.blob = blob
        self.n = n
        self.max_qos = max_qos
        self.spans = spans
        self.sent_at: Optional[float] = None  # None = not sent yet
        self.retx = 0


class _FwdPeer:
    """Per-peer sender state for at-least-once window forwarding:
    monotonic frame sequence, bounded in-flight replay buffer, and
    the failure-driven breaker (closed -> suspect -> open, probed
    back closed — the PR 1 device-breaker shape on a peer link)."""

    __slots__ = ("seq", "inflight", "fail_streak", "suspect",
                 "breaker_open", "next_probe", "acked", "shed")

    def __init__(self) -> None:
        self.seq = 0
        # seq -> _FwdFrame, insertion-ordered (seqs ascend), so the
        # first entry is always the OLDEST unacked frame
        self.inflight: "OrderedDict[int, _FwdFrame]" = OrderedDict()
        self.fail_streak = 0
        self.suspect = False
        self.breaker_open = False
        self.next_probe = 0.0
        self.acked = 0  # frames confirmed (stats)
        self.shed = 0   # messages dropped by overflow/departure (stats)



def _props_to_wire(props: Dict[str, Any]) -> Dict[str, Any]:
    """MQTT 5 properties JSON-safely: bytes values (correlation_data,
    authentication_data) wrap as {"$b64": ...}."""
    out: Dict[str, Any] = {}
    for k, v in props.items():
        if isinstance(v, (bytes, bytearray)):
            out[k] = {"$b64": pack_bytes(bytes(v))}
        elif isinstance(v, list):
            out[k] = [
                list(p) if isinstance(p, tuple) else p for p in v
            ]  # user_property pairs
        else:
            out[k] = v
    return out


def _props_from_wire(props: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in props.items():
        if isinstance(v, dict) and set(v) == {"$b64"}:
            out[k] = unpack_bytes(v["$b64"])
        elif isinstance(v, list):
            out[k] = [tuple(p) if isinstance(p, list) else p for p in v]
        else:
            out[k] = v
    return out


def msg_to_wire(msg: Message) -> Dict[str, Any]:
    return {
        "topic": msg.topic,
        "payload": pack_bytes(msg.payload),
        "qos": msg.qos,
        "retain": msg.retain,
        "from_client": msg.from_client,
        "from_username": msg.from_username,
        "mid": pack_bytes(msg.mid),
        "timestamp": msg.timestamp,
        "properties": _props_to_wire(msg.properties),
        "sys": msg.sys,
        "dup": msg.dup,
        # broker-internal headers must survive intra-cluster forwarding:
        # losing `cluster_origin` on the hop would make the peer node's
        # LinkServer re-export imported traffic (gossip), and losing
        # `link_egress` would make its delivery guard drop legitimate
        # $LINK/msg deliveries (only JSON-scalar values cross the wire)
        "headers": {
            k: v for k, v in msg.headers.items()
            if isinstance(v, (str, int, float, bool)) or v is None
        },
    }


def _fwd_spans(msgs) -> list:
    """Pending forward spans riding a buffered window's traced copies
    (unsampled messages carry none)."""
    out = []
    for m in msgs:
        span = getattr(m, "_trace_fwd", None)
        if span is not None:
            out.append(span)
    return out


def strip_wire_trace_ctx(wires) -> None:
    """Strip the lifecycle-trace user property from wire-form message
    dicts IN PLACE.  Used on paths that hand wires to a session mqueue
    WITHOUT passing a broker ingress (quorum-orphan storage → restore):
    everywhere else the receiving node's ingress strips the carrier."""
    from ..tracecontext import extract_strip

    for w in wires:
        props = w.get("properties")
        if props:
            extract_strip(props)


def msg_from_wire(obj: Dict[str, Any]) -> Message:
    return Message(
        topic=obj["topic"],
        payload=unpack_bytes(obj["payload"]),
        qos=obj.get("qos", 0),
        retain=obj.get("retain", False),
        from_client=obj.get("from_client", ""),
        from_username=obj.get("from_username"),
        mid=unpack_bytes(obj["mid"]),
        timestamp=obj.get("timestamp", 0.0),
        properties=_props_from_wire(obj.get("properties") or {}),
        sys=obj.get("sys", False),
        dup=obj.get("dup", False),
        headers=dict(obj.get("headers") or {}),
    )


class ClusterNode:
    def __init__(
        self,
        name: str,
        broker,
        bind: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval: float = 0.5,
        down_after: float = 2.0,
        flush_interval: float = 0.005,
        flush_max: int = 1000,
        consensus: str = "raft",  # raft (default) | lww
        raft_data_dir: Optional[str] = None,
        raft_fsync: bool = True,
        sharded_routes: bool = False,
        role: str = "core",  # core | replicant
        transport_mode: str = "tcp",  # tcp | quic | auto
        quic_psk: str = "",
        fwd_inflight_max: int = 512,
        fwd_ack_timeout: float = 1.0,
        fwd_backoff_max: float = 5.0,
        fwd_suspect_threshold: int = 3,
        fwd_breaker_threshold: int = 8,
        fwd_probe_interval: float = 1.0,
    ) -> None:
        self.name = name
        self.broker = broker
        # mria's core/replicant split: CORES form the raft quorums and
        # bear the write path; REPLICANTS never vote or count toward a
        # majority — they serve clients, replicate routes/clients/conf
        # through the same LWW streams, and submit quorum writes BY
        # FORWARDING to a core.  Scaling the serving tier then never
        # slows consensus down (adding replicants leaves quorum size
        # untouched), exactly why the reference splits the roles.
        self.role = role
        if role == "replicant" and consensus == "raft":
            consensus = "lww"  # local consensus machinery stays off
        # "raft" upgrades the conf journal and DS replication from
        # best-effort LWW to quorum commit (VERDICT r3 missing #1):
        # an acked write survives any single node failure
        self.consensus = consensus
        self.raft_data_dir = raft_data_dir
        self.raft_fsync = raft_fsync
        self.raft_conf = None
        self.raft_ds = None
        # the inter-node link layer: TCP always listens; quic/auto
        # additionally bind the QUIC UDP endpoint on the same port
        # number and dial peers over it (auto degrades per peer to
        # TCP on handshake failure and re-probes — see transport.py)
        self.transport = NodeTransport(
            name, bind, port,
            transport_mode=transport_mode,
            quic_psk=hashlib.sha256(
                b"emqx_tpu-cluster-psk:" + quic_psk.encode()
            ).digest(),
        )
        self.routes = ClusterRouteTable()
        # at-least-once window forwarding (lww/async mode; raft mode
        # confirms through forward_sync instead): per-peer sequenced
        # frames held in a bounded replay buffer until acked
        self.fwd_inflight_max = fwd_inflight_max
        self.fwd_ack_timeout = fwd_ack_timeout
        self.fwd_backoff_max = fwd_backoff_max
        self.fwd_suspect_threshold = fwd_suspect_threshold
        self.fwd_breaker_threshold = fwd_breaker_threshold
        self.fwd_probe_interval = fwd_probe_interval
        self._fwd_out: Dict[str, _FwdPeer] = {}
        # receiver dedup: origin -> [epoch, floor, seen-set]; a frame
        # with seq <= floor or in seen is a retransmit duplicate —
        # re-acked, never re-dispatched (at-least-once stays
        # at-least-once, not duplicate-dispatch)
        self._fwd_in: Dict[str, List] = {}
        self._fwd_rng = random.Random(hash(name) & 0xFFFFFFFF)
        # sharded mode: the cluster's filter set is PARTITIONED by
        # rendezvous hash instead of fully replicated — each node
        # indexes ~1/N of it and publish windows scatter-gather
        # (cluster/sharded_routes.py).  self.routes then holds only
        # this node's own filters (for sync compat), never peers'.
        self.shard = None
        if sharded_routes:
            from .sharded_routes import ShardedRouteIndex

            self.shard = ShardedRouteIndex(self)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.heartbeat_interval = heartbeat_interval
        self.down_after = down_after
        self.flush_interval = flush_interval
        self.flush_max = flush_max
        # peers: name -> (host, port); alive tracking by last heartbeat
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._peer_roles: Dict[str, str] = {}
        self._last_seen: Dict[str, float] = {}
        self._down: set = set()
        self._synced: set = set()  # peers whose full sync succeeded
        self._pending_ops: List[Tuple[int, str, str]] = []  # (seq, op, flt)
        # versioned route-op stream: every local op gets a monotonic seq
        # and casts carry (epoch, seq).  A full-sync snapshot carries the
        # seq it was cut at, so the receiver can purge-and-replace
        # without losing ops that raced past the snapshot on the other
        # TCP connection (sync replies and casts are unordered): ops in
        # the per-peer log with seq > snapshot seq are re-applied after
        # the snapshot.  The epoch (one per process incarnation)
        # invalidates the log across a peer restart.
        self._epoch = time.time_ns()
        self._op_seq = 0
        self._peer_epoch: Dict[str, int] = {}
        self._peer_seq: Dict[str, int] = {}
        self._op_log: Dict[str, deque] = {}
        self._flush_wakeup = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self._fwd_tasks: set = set()
        self._started = False

        # replicated client registry: clientid -> owning node (the
        # emqx_cm_registry role, emqx_cm_registry.erl:161) — drives
        # cross-node session takeover on reconnect-elsewhere
        self.clients: Dict[str, str] = {}
        # cluster config journal: per-path last-writer-wins ordered by
        # (counter, node) — total order, so every node converges to the
        # same value for every path regardless of arrival order
        self._conf_counter = 0
        self._conf_latest: Dict[str, Tuple[int, str, Any]] = {}
        self._pending_fwd: Dict[str, List[Message]] = {}
        # DS replication: this node's replica copies of peers' sessions
        # (buffer bound mirrors the owner's mqueue depth)
        self.replicas = ReplicaStore(
            cap_per_client=broker.config.mqtt.max_mqueue_len
        )
        self._pending_repl: List[Tuple[str, Dict]] = []
        # raft mode: DS entries awaiting the next quorum flush, plus
        # the in-flight quorum tasks a PUBACK barrier must also await
        # (the background flush loop may hold a window's entries
        # mid-commit when the barrier runs)
        self._pending_repl_raft: List[Dict] = []
        self._quorum_inflight: set = set()

        self.transport.on("route_ops", self._handle_route_ops)
        self.transport.on("takeover", self._handle_takeover)
        self.transport.on("client_discard", self._handle_client_discard)
        self.transport.on("conf_txn", self._handle_conf_txn)
        self.transport.on("ds_ckpt", self._handle_ds_ckpt)
        self.transport.on("ds_msgs", self._handle_ds_msgs)
        self.transport.on("ds_take", self._handle_ds_take)
        self.transport.on("forward_batch", self._handle_forward_batch)
        self.transport.on("fwd_ack", self._handle_fwd_ack)
        # concurrent: this handler AWAITS a raft commit whose quorum
        # traffic may share the inbound connection — inline it would
        # deadlock-by-stall every failover window
        self.transport.on("forward_sync", self._handle_forward_sync,
                          concurrent=True)
        self.transport.on("heartbeat", self._handle_heartbeat)
        self.transport.on("node_info", self._handle_node_info)
        self.transport.on("conn_count", self._handle_conn_count)
        self.transport.on("rebalance_shed", self._handle_rebalance_shed)
        self.transport.on("session_purge", self._handle_session_purge)
        self.transport.on("sync", self._handle_sync)
        # replicant-forwarded config writes land on a core (concurrent:
        # the handler awaits a raft commit whose traffic may share the
        # inbound link)
        self.transport.on("conf_fwd", self._handle_conf_fwd,
                          concurrent=True)
        if self.shard is not None:
            self.transport.on("shard_ops", self.shard.handle_ops)
            self.transport.on("shard_sync", self.shard.handle_sync)
            # concurrent: a shard_match may arrive while this node's
            # own scatter call is outstanding on the same link pair —
            # inline handling would deadlock the two calls against
            # each other
            self.transport.on("shard_match", self.shard.handle_match,
                              concurrent=True)

        # wire into the broker: route-change notifications + forward
        broker.router.on_route_added = self._route_added
        broker.router.on_route_removed = self._route_removed
        broker.external = self
        # adopt routes created before the cluster layer attached (e.g.
        # boot-advertised persistent-session filters after a restart) so
        # the initial full sync carries them to peers
        if self.shard is not None:
            # sharded: the first resync (post-join) announces every
            # local filter to its owner
            self.shard.resync_due = True
        else:
            for flt in broker.router.topics():
                self.routes.add_route(flt, self.name)

    # ------------------------------------------------------- lifecycle

    async def start(self, seeds: Optional[List[Tuple[str, str, int]]] = None):
        """Start the transport and join via seed nodes (ekka static
        discovery analogue): exchange full route sets with each seed."""
        await self.transport.start()
        self._started = True
        self._loop = asyncio.get_running_loop()
        for name, host, port in seeds or ():
            self.add_peer(name, host, port)
        if self.consensus == "raft":
            from .raft import RaftNode

            peers = list(self._peers)
            self.raft_conf = RaftNode(
                self.name, peers, self.transport,
                apply_cb=self._raft_conf_apply,
                data_dir=self.raft_data_dir, group="conf",
                fsync=self.raft_fsync,
            )
            self.raft_ds = RaftNode(
                self.name, peers, self.transport,
                apply_cb=self._raft_ds_apply,
                data_dir=self.raft_data_dir, group="ds",
                fsync=self.raft_fsync,
            )
            self.raft_conf.start()
            self.raft_ds.start()
            # membership is STATIC — the seed set at start (the
            # reference's ra clusters are likewise explicit; joint
            # consensus for online membership change is out of scope).
            # Peers learned later via gossip replicate routes but do
            # not join the quorum.
            if not peers:
                log.warning(
                    "%s: raft consensus with NO peers — single-node "
                    "quorum, entries commit locally only", self.name,
                )
            else:
                log.info("%s: raft membership frozen to %s",
                         self.name, sorted([self.name] + peers))
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._flush_loop()),
            loop.create_task(self._heartbeat_loop()),
            loop.create_task(self._fwd_retx_loop()),
        ]
        for name in list(self._peers):
            # deliberate snapshot iteration; a peer removed while an
            # earlier sync is in flight just gets one harmless extra
            # sync (_sync_with is idempotent full-state resend)
            # brokerlint: ignore[RACE801]
            await self._sync_with(name)

    async def stop(self) -> None:
        self._started = False
        # take the task list BEFORE the first await: a start() racing
        # mid-stop repopulates self._tasks, and the old
        # `self._tasks = []` after the reap loop would silently drop
        # (leak, never cancel) those new tasks
        tasks, self._tasks = self._tasks, []
        for t in tasks:
            t.cancel()  # request them all first, then reap
        for t in tasks:
            await cancel_and_wait(t)
        if self.raft_conf is not None:
            await self.raft_conf.stop()
        if self.raft_ds is not None:
            await self.raft_ds.stop()
        await self.transport.stop()

    def add_peer(self, name: str, host: str, port: int) -> None:
        if name == self.name:
            return
        self._peers[name] = (host, port)
        self.transport.add_peer(name, host, port)
        self._last_seen.setdefault(name, time.monotonic())

    @property
    def port(self) -> int:
        return self.transport.port

    def peers_alive(self) -> List[str]:
        return [p for p in self._peers if p not in self._down]

    # ----------------------------------------------- route replication

    def _route_added(self, flt: str) -> None:
        if self.shard is not None:
            self.shard.local_op("add", flt)
            return
        self.routes.add_route(flt, self.name)
        self._queue_op("add", flt)

    def _route_removed(self, flt: str) -> None:
        if self.shard is not None:
            self.shard.local_op("del", flt)
            return
        self.routes.delete_route(flt, self.name)
        self._queue_op("del", flt)

    def _queue_op(self, op: str, flt: str) -> None:
        if not self._started:
            return
        self._op_seq += 1
        self._pending_ops.append((self._op_seq, op, flt))
        if len(self._pending_ops) >= self.flush_max:
            self._flush_wakeup.set()

    async def _flush_loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(
                    self._flush_wakeup.wait(), self.flush_interval
                )
            except asyncio.TimeoutError:
                pass
            # clear BEFORE snapshotting _pending_ops (loop-atomic up
            # to the take at the append below): an op enqueued during
            # the casts re-sets the event and the next round flushes
            # it — the pair is torn by design, never lost
            # brokerlint: ignore[RACE804]
            self._flush_wakeup.clear()
            casts = []
            if self._pending_ops:
                ops, self._pending_ops = self._pending_ops, []
                casts.append(
                    {
                        "type": "route_ops",
                        "node": self.name,
                        "epoch": self._epoch,
                        "ops": ops,
                    }
                )
            for obj in casts:
                await asyncio.gather(
                    *(
                        self.transport.cast(p, obj)
                        for p in self.peers_alive()
                    ),
                    return_exceptions=True,
                )
            if self._pending_fwd:
                if self.raft_ds is not None:
                    # raft mode forwards go commit-confirmed (tracked:
                    # the PUBACK barrier awaits in-flight drains)
                    self._track_quorum(self._forward_sync_drain())
                else:
                    await self._flush_forwards()
            if self._pending_repl:
                # _flush_replication re-snapshots _pending_repl itself
                # (take-and-swap); this check is only an elision
                # brokerlint: ignore[RACE801]
                await self._flush_replication()
            if self._pending_repl_raft:
                # background quorum flush (bounded staleness for sync
                # callers; the batcher's barrier gates PUBACKs itself)
                self._track_quorum(self.flush_ds())
            if self.shard is not None and self.shard.has_work:
                await self.shard.flush()

    def _check_epoch(self, node: str, epoch: int) -> None:
        """A new epoch means the peer restarted: its op stream starts
        over, so the buffered log from the old incarnation is garbage."""
        if self._peer_epoch.get(node) != epoch:
            self._peer_epoch[node] = epoch
            self._peer_seq[node] = 0
            self._op_log[node] = deque(maxlen=8192)

    async def _handle_route_ops(self, peer: str, obj: Dict) -> None:
        """One ordered op stream per peer: route ops (add/del on a
        filter) and client-registry ops (cadd/cdel on a clientid)."""
        node = obj.get("node", peer)
        self._check_epoch(node, obj.get("epoch", 0))
        log_ = self._op_log[node]
        for seq, op, arg in obj.get("ops", ()):
            if seq <= self._peer_seq.get(node, 0):
                # already reflected by an applied snapshot (or a dup):
                # re-applying a stale delete would transiently remove a
                # route the snapshot re-asserted
                continue
            if op == "add":
                self.routes.add_route(arg, node)
            elif op == "del":
                self.routes.delete_route(arg, node)
            elif op == "cadd":
                self.clients[arg] = node
                # the session is live on `node` now: any replica held
                # here is stale (fresh replication will follow).  In
                # raft mode the replicas ARE the quorum store — never
                # dropped on ownership changes, only overwritten by
                # newer committed checkpoints
                if self.raft_ds is None:
                    self.replicas.drop(arg)
            elif op == "cdel":
                if self.clients.get(arg) == node:
                    del self.clients[arg]
                    # only the CURRENT owner's close invalidates the
                    # replica; a lagging cdel from a previous owner must
                    # not destroy the new owner's fresh checkpoint
                    if self.raft_ds is None:
                        self.replicas.drop(arg)
            log_.append((seq, op, arg))
            self._peer_seq[node] = seq

    def _apply_snapshot(
        self, node: str, filters: List[str], snap_seq: int
    ) -> None:
        """Purge-and-replace `node`'s routes from a full-sync snapshot,
        then re-apply any ops that raced past the snapshot cut (casts
        travel on a different connection than the sync reply, so a
        freshly added route may already be applied locally while absent
        from the snapshot — a blind purge would silently drop it)."""
        self.routes.purge_node(node)
        for flt in filters:
            self.routes.add_route(flt, node)
        for seq, op, flt in self._op_log.get(node, ()):
            if seq > snap_seq and op in ("add", "del"):
                if op == "add":
                    self.routes.add_route(flt, node)
                else:
                    self.routes.delete_route(flt, node)
        self._peer_seq[node] = max(self._peer_seq.get(node, 0), snap_seq)

    async def _sync_with(self, peer: str) -> None:
        """Full bidirectional route exchange (the mria bootstrap copy a
        joining node gets).  Failure is retried from the heartbeat loop
        until it succeeds — a joiner must not silently miss pre-existing
        routes.  Sharded mode skips the route payloads (no full
        replica exists to exchange) and schedules a shard resync
        instead — the membership just changed from this node's view."""
        reply = await self.transport.call(
            peer,
            {
                "type": "sync",
                "node": self.name,
                "role": self.role,
                "listen": [self.transport.bind, self.transport.port],
                "epoch": self._epoch,
                "seq": self._op_seq,
                "routes": (
                    [] if self.shard is not None else self._local_routes()
                ),
                "clients": self._local_clients(),
                "conf": self._conf_dump(),
                "peers": self._peer_list(),
            },
        )
        if reply is None:
            self._synced.discard(peer)
            return
        self._mark_alive(peer)
        self._synced.add(peer)
        self._peer_roles[peer] = reply.get("role", "core")
        if self.shard is not None:
            self.shard.on_membership_change()
        self._check_epoch(peer, reply.get("epoch", 0))
        self._apply_clients(
            peer, reply.get("clients", ()), reply.get("seq", 0)
        )
        for cnt, node, path, value in reply.get("conf", ()):
            self._conf_apply((cnt, node), path, value)
        self._adopt_peers(reply.get("peers", ()))
        # split the reply: the responder's own routes purge-and-replace
        # (seq-guarded); third-party routes are add-only hints, so force
        # a direct (purge-and-replace) sync with each of those nodes to
        # reconcile anything stale the responder still carried
        own: List[str] = []
        changed_third_party: set = set()
        for entry in reply.get("routes", ()):
            for node in entry["nodes"]:
                if node == peer:
                    own.append(entry["topic"])
                elif node != self.name:
                    if self.routes.add_route(entry["topic"], node):
                        # the responder taught us something about a node
                        # we thought we were synced with — it may be a
                        # stale phantom, so re-sync with that node
                        # directly (no-op churn avoided: an already-known
                        # route triggers nothing)
                        changed_third_party.add(node)
        self._apply_snapshot(peer, own, reply.get("seq", 0))
        self._synced -= changed_third_party  # heartbeat loop re-syncs

    async def _handle_sync(self, peer: str, obj: Dict) -> Dict:
        node = obj.get("node", peer)
        self._peer_roles[node] = obj.get("role", "core")
        self._learn_peer(node, obj.get("listen"))
        self._mark_alive(node)
        # peer's local routes replace whatever we had for it (seq-guarded
        # against its own racing casts, same as the requester side)
        self._check_epoch(node, obj.get("epoch", 0))
        self._apply_snapshot(node, obj.get("routes", ()), obj.get("seq", 0))
        self._apply_clients(node, obj.get("clients", ()), obj.get("seq", 0))
        for cnt, n2, path, value in obj.get("conf", ()):
            self._conf_apply((cnt, n2), path, value)
        self._adopt_peers(obj.get("peers", ()))
        if self.shard is not None:
            self.shard.on_membership_change()
        return {
            "role": self.role,
            "routes": (
                [] if self.shard is not None else self.routes.all_routes()
            ),
            "clients": self._local_clients(),
            "conf": self._conf_dump(),
            "peers": self._peer_list(),
            "epoch": self._epoch,
            "seq": self._op_seq,
        }

    def _peer_list(self) -> List[List]:
        """Known peers with addresses (membership gossip: a joiner that
        only seeded one node learns the full mesh at sync time)."""
        return [
            [n, h, p] for n, (h, p) in self._peers.items()
        ]

    def _adopt_peers(self, peers) -> None:
        for entry in peers:
            name, host, port = entry[0], entry[1], int(entry[2])
            if name != self.name and name not in self._peers:
                self.add_peer(name, host, port)
            if name != self.name and self._peer_roles.get(
                name, "core"
            ) == "core":
                for grp in (self.raft_conf, self.raft_ds):
                    if grp is not None:
                        grp.add_member(name)

    def _local_clients(self) -> List[str]:
        return sorted(
            cid for cid, n in self.clients.items() if n == self.name
        )

    def _apply_clients(self, node: str, cids, snap_seq: int = 0) -> None:
        """Purge-and-replace `node`'s client-registry claims, then
        re-apply client ops that raced past the snapshot (same seq
        guard as the route snapshot)."""
        for cid, n in list(self.clients.items()):
            if n == node:
                del self.clients[cid]
        for cid in cids:
            self.clients[cid] = node
        for seq, op, cid in self._op_log.get(node, ()):
            if seq > snap_seq and op in ("cadd", "cdel"):
                if op == "cadd":
                    self.clients[cid] = node
                elif self.clients.get(cid) == node:
                    del self.clients[cid]

    def _learn_peer(self, node: str, listen) -> None:
        """Adopt a peer advertised in a sync/heartbeat message so
        membership is symmetric without manual add_peer on both sides.
        In raft mode a gossip-learned peer also joins the quorum while
        the log is still empty (chained bring-up: n1 alone, n2 seeding
        n1, n3 seeding n1 — every node must converge on the same
        membership before the first commit)."""
        if node != self.name and node not in self._peers and listen:
            self.add_peer(node, listen[0], int(listen[1]))
        if node != self.name and self._peer_roles.get(
            node, "core"
        ) == "core":
            # replicants never join the quorum (mria core/replicant)
            for grp in (self.raft_conf, self.raft_ds):
                if grp is not None:
                    grp.add_member(node)

    def _local_routes(self) -> List[str]:
        return sorted(self.routes.routes_of(self.name))

    # ------------------------------------------------- client registry

    def client_opened(self, clientid: str) -> None:
        self.clients[clientid] = self.name
        # a locally opened session invalidates any replica WE hold for
        # it (peers drop theirs via the cadd op).  NOT in raft mode:
        # there the replicas are the quorum store — an adopter that
        # dropped its copy at adoption would lose the log tail that
        # commits just after the import (newer checkpoints simply
        # overwrite instead)
        if self.raft_ds is None:
            self.replicas.drop(clientid)
        self._queue_client_op("add", clientid)
        self._submit_reg("cadd", clientid)

    def client_closed(self, clientid: str) -> None:
        if self.raft_ds is None:
            self.replicas.drop(clientid)
        if self.clients.get(clientid) == self.name:
            del self.clients[clientid]
            self._queue_client_op("del", clientid)
            self._submit_reg("cdel", clientid)

    def _submit_reg(self, op: str, clientid: str) -> None:
        """Raft mode: client-registry ops are ALSO committed through
        the conf log, so ownership claims replay in one total order on
        every member — two sides of a healed partition converge to the
        same owner per clientid instead of whichever LWW cast landed
        last (the widened quorum plane, VERDICT r4 #8).  The local
        apply + LWW cast above stay for liveness (a minority-partition
        node keeps serving its own clients); the committed log is the
        convergence authority."""
        if self.raft_conf is None or not self._started:
            return
        self._track_quorum(self._submit_reg_async(op, clientid))

    async def _submit_reg_async(self, op: str, clientid: str) -> None:
        try:
            await self.raft_conf.submit(
                {"kind": "reg", "op": op, "cid": clientid,
                 "node": self.name},
                timeout=10.0,
            )
        except Exception:
            # minority partition: the op stays applied locally and the
            # post-heal sync re-announces it; losing the log entry only
            # delays convergence
            log.warning("%s: registry %s(%s) not quorum-committed",
                        self.name, op, clientid)

    def _queue_client_op(self, op: str, clientid: str) -> None:
        if not self._started:
            return
        # client ops ride the SAME ordered op stream as route ops (one
        # shared seq, one cast sequence): separate casts would re-order
        # against each other and break the per-peer seq guard
        self._op_seq += 1
        self._pending_ops.append((self._op_seq, "c" + op, clientid))
        if len(self._pending_ops) >= self.flush_max:
            self._flush_wakeup.set()

    def remote_owner(self, clientid: str) -> Optional[str]:
        """The live peer owning this client's session, if any."""
        owner = self.clients.get(clientid)
        if owner is None or owner == self.name or owner in self._down:
            return None
        return owner

    # --------------------------------------------- DS replication

    def _buddy(self, clientid: str) -> Optional[str]:
        peers = self.peers_alive()
        if not peers:
            return None
        return rendezvous_pick(clientid, peers, 1)[0]

    def replicate_checkpoint(
        self, clientid: str, subs: Dict, expiry: float, queued: List[Dict]
    ) -> None:
        """Ship a persistent session's checkpoint (+ its pending
        messages) to the clientid's buddy peer.  Buffered into the SAME
        flush cycle as the op stream: a checkpoint cast overtaking the
        connect's still-buffered cadd op would be dropped as stale by
        the receiver."""
        state = {
            "subs": subs,
            "expiry": expiry,
            "queued": queued,
            "saved_at": time.time(),
        }
        if self.raft_ds is not None:
            self._pending_repl_raft.append(
                {"kind": "ckpt", "clientid": clientid, "state": state}
            )
            self._kick_raft_flush()
            return
        buddy = self._buddy(clientid)
        if buddy is None:
            return
        obj = {"type": "ds_ckpt", "clientid": clientid, "state": state}
        self._pending_repl.append((buddy, obj))
        self._flush_wakeup.set()

    def replicate_queued(self, clientid: str, wire_msgs: List[Dict]) -> None:
        """Buffer per-client queued-message replication; flushed with
        the op stream (ordering, see replicate_checkpoint)."""
        if self.raft_ds is not None:
            self._pending_repl_raft.append(
                {"kind": "msgs", "clientid": clientid,
                 "messages": wire_msgs}
            )
            self._kick_raft_flush()
            return
        buddy = self._buddy(clientid)
        if buddy is None:
            return
        self._pending_repl.append(
            (buddy, {"type": "ds_msgs", "clientid": clientid,
                     "messages": wire_msgs})
        )
        if len(self._pending_repl) >= self.flush_max:
            self._flush_wakeup.set()

    def _kick_raft_flush(self) -> None:
        """Background quorum flush for callers that don't await the
        barrier themselves (sync paths); the publish batcher calls
        `quorum_barrier` directly to gate PUBACKs."""
        if len(self._pending_repl_raft) >= self.flush_max:
            self._track_quorum(self.flush_ds())

    async def _flush_replication(self) -> None:
        pending, self._pending_repl = self._pending_repl, []
        for buddy, obj in pending:
            # sent inline (not as a task): per-link FIFO keeps these
            # ORDERED AFTER the op casts flushed this same cycle
            await self.transport.cast(buddy, obj)

    async def _handle_ds_ckpt(self, peer: str, obj: Dict) -> None:
        self.replicas.store_checkpoint(
            obj.get("clientid", ""), obj.get("state", {})
        )

    async def _handle_ds_msgs(self, peer: str, obj: Dict) -> None:
        self.replicas.append_messages(
            obj.get("clientid", ""), obj.get("messages", [])
        )

    async def _handle_ds_take(self, peer: str, obj: Dict) -> Dict:
        # NON-destructive peek: if the reply is lost (timeout, link
        # drop) the only surviving copy must not vanish with it.  The
        # claimant's session-open broadcasts cadd, which is what drops
        # this replica once the restore actually succeeded.
        return {"state": self.replicas.peek(obj.get("clientid", ""))}

    def merge_replica_into(self, session) -> int:
        """Raft mode: fold the LOCAL quorum-replica copy's messages
        into a locally-resuming session's mqueue.  An adopter's import
        races the tail of the log — entries committed just after the
        adoption live only in the replica store — so a resume that
        never goes through fetch_session would drop them.  Dedup by
        mid against what the session already holds (at-least-once:
        duplicates beat losses)."""
        if self.raft_ds is None:
            return 0
        rep = self.replicas.peek(session.clientid, mark_orphans=True)
        if not rep or not rep.get("queued"):
            return 0
        seen = {m.mid for m in session.mqueue}
        for entry in session.inflight.values():
            if getattr(entry, "msg", None) is not None:
                seen.add(entry.msg.mid)
        merged = 0
        for wire in rep["queued"]:
            m = msg_from_wire(wire)
            if m.mid in seen:
                continue
            session.mqueue.insert(m)
            merged += 1
        if merged:
            self.broker.metrics.inc("session.replica_merged", merged)
        return merged

    async def fetch_session(self, clientid: str) -> Optional[Dict]:
        """Locate a reconnecting client's session anywhere in the
        cluster: live owner takeover first, then replica stores — this
        node's, then the rendezvous buddy, then the remaining peers
        CONCURRENTLY (a hung peer must not serialize a reconnect
        storm)."""
        state = await self.takeover(clientid)
        if state is not None:
            if self.raft_ds is not None:
                # the live owner may be an ADOPTER whose import raced
                # the tail of the quorum log (entries committed just
                # after adoption live only in the replica store):
                # merge the local replica copy, deduplicating by mid —
                # QoS1 is at-least-once, a duplicate beats a loss
                rep = self.replicas.peek(clientid, mark_orphans=True)
                if rep and rep.get("queued"):
                    seen = {
                        m.get("mid") for m in state.get("queued", ())
                    }
                    extra = [
                        m for m in rep["queued"]
                        if m.get("mid") not in seen
                    ]
                    if extra:
                        state["queued"] = (
                            list(state.get("queued", ())) + extra
                        )
            return state
        state = self.replicas.take(clientid)
        if state is not None:
            self.broker.metrics.inc("session.replica_restored")
            return state
        # the replica lives on the clientid's rendezvous buddy: one
        # bounded RPC — never a full-cluster sweep, so a connect storm
        # of brand-new persistent clients costs one fast miss each.
        # (After a membership change the historical buddy may differ;
        # that miss is within the documented best-effort model.)
        buddy = self._buddy(clientid)
        if buddy is None:
            return None
        reply = await self.transport.call(
            buddy, {"type": "ds_take", "clientid": clientid}, timeout=1.0
        )
        if reply and reply.get("state"):
            self.broker.metrics.inc("session.replica_restored")
            return reply["state"]
        return None

    # ------------------------------------------- cluster-wide config

    def update_config(self, path: str, value) -> Tuple[int, str]:
        """Apply a config update cluster-wide (the emqx_conf /
        emqx_cluster_rpc multicall role, emqx_cluster_rpc.erl:26-54).
        In "raft" consensus the update is a LOG ENTRY: every node
        applies all updates in one committed order, so racing writes
        to a path resolve to the same deterministic winner everywhere
        (the reference's logged transactional multicall; "lww" keeps
        round-3's per-path last-writer-wins journal)."""
        if self.raft_conf is not None:
            loop = asyncio.get_running_loop()
            task = loop.create_task(self._submit_conf(path, value))
            self._fwd_tasks.add(task)
            task.add_done_callback(self._fwd_tasks.discard)
            self._conf_counter += 1
            return (self._conf_counter, self.name)
        if self.role == "replicant":
            core = self._any_core()
            if core is not None:
                # fire the forward; the committed entry comes back via
                # the cores' replicant broadcast
                loop = asyncio.get_running_loop()
                task = loop.create_task(self.transport.call(
                    core, {"type": "conf_fwd", "path": path,
                           "value": value}, timeout=10.0,
                ))
                self._fwd_tasks.add(task)
                task.add_done_callback(self._fwd_tasks.discard)
                self._conf_counter += 1
                return (self._conf_counter, core)
        self._conf_counter += 1
        txn = (self._conf_counter, self.name)
        self._conf_apply(txn, path, value)
        obj = {
            "type": "conf_txn",
            "node": self.name,
            "txns": [[txn[0], txn[1], path, value]],
        }
        loop = asyncio.get_running_loop()
        for p in self.peers_alive():
            task = loop.create_task(self.transport.cast(p, obj))
            self._fwd_tasks.add(task)
            task.add_done_callback(self._fwd_tasks.discard)
        return txn

    async def update_config_async(self, path: str, value) -> Tuple[int, str]:
        """Raft-mode config update that PROPAGATES failures to the
        caller (the management API awaits this): returns once the
        entry is committed on a majority.  Replicants forward to a
        core and await its commit."""
        if self.role == "replicant":
            core = self._any_core()
            if core is None:
                raise ConnectionError("replicant: no core reachable")
            rep = await self.transport.call(
                core, {"type": "conf_fwd", "path": path,
                       "value": value}, timeout=10.0,
            )
            if not rep or not rep.get("ok"):
                raise ConnectionError(
                    f"core {core} rejected forwarded conf update"
                )
            return (int(rep.get("index", 0)), core)
        if self.raft_conf is None:
            return self.update_config(path, value)
        idx = await self._submit_conf(path, value, retries=0)
        return (idx, "raft")

    def _any_core(self) -> Optional[str]:
        for p in self.peers_alive():
            if self._peer_roles.get(p, "core") == "core":
                return p
        return None

    async def _handle_conf_fwd(self, peer: str, obj: Dict) -> Dict:
        """A replicant forwarded a config write: commit it here (the
        mria write-on-core path)."""
        try:
            txn = await self.update_config_async(
                obj["path"], obj["value"]
            )
            return {"ok": True, "index": txn[0]}
        except Exception as exc:
            return {"ok": False, "error": str(exc)}

    async def _submit_conf(self, path: str, value,
                           retries: int = 3) -> int:
        """Submit with bounded retries (leadership churn); a final
        failure is LOUD — a silently vanished config transaction is
        worse than a failed API call."""
        for attempt in range(retries + 1):
            try:
                return await self.raft_conf.submit(
                    {"path": path, "value": value}
                )
            except Exception:
                if attempt == retries:
                    log.exception(
                        "cluster config update %r LOST after %d "
                        "attempts", path, retries + 1,
                    )
                    raise
                await asyncio.sleep(0.5)

    def _conf_apply(self, txn: Tuple[int, str], path: str, value) -> None:
        """Apply iff this txn is the newest for its path (LWW by the
        (counter, node) total order): a concurrently minted older txn
        arriving later is journal-recorded but never clobbers state, so
        all nodes converge."""
        self._conf_counter = max(self._conf_counter, txn[0])
        cur = self._conf_latest.get(path)
        if cur is not None and (cur[0], cur[1]) >= txn:
            return
        self._conf_latest[path] = (txn[0], txn[1], value)
        try:
            self.broker.apply_config(path, value)
        except Exception:
            log.exception("cluster config txn %s failed for %s", txn, path)

    def _conf_dump(self) -> List[List]:
        """Per-path compaction: the latest txn for EVERY path, so a late
        joiner catches up completely regardless of journal age."""
        return [
            [cnt, node, path, value]
            for path, (cnt, node, value) in self._conf_latest.items()
        ]

    async def _handle_conf_txn(self, peer: str, obj: Dict) -> None:
        for cnt, node, path, value in obj.get("txns", ()):
            self._conf_apply((cnt, node), path, value)

    # -------------------------------------------- raft state machines

    def _raft_conf_apply(self, index: int, payload: Dict) -> None:
        """Committed config entries apply in LOG order on every node
        — the deterministic total order emqx_cluster_rpc gets from its
        mnesia transaction log.  Registry ("reg") entries share the
        log: ownership claims replay identically everywhere, so healed
        partitions converge per clientid."""
        if self.role == "core":
            # replicants are outside the quorum: hand them every
            # committed entry over the LWW conf stream
            reps = [p for p in self.peers_alive()
                    if self._peer_roles.get(p) == "replicant"]
            if reps and payload.get("kind") != "reg":
                self._conf_counter += 1
                obj = {"type": "conf_txn", "node": self.name,
                       "txns": [[self._conf_counter, self.name,
                                 payload["path"], payload["value"]]]}
                loop = asyncio.get_running_loop()
                for p in reps:
                    t = loop.create_task(self.transport.cast(p, obj))
                    self._fwd_tasks.add(t)
                    t.add_done_callback(self._fwd_tasks.discard)
        if payload.get("kind") == "reg":
            cid, node = payload.get("cid", ""), payload.get("node", "")
            if payload.get("op") == "cadd":
                self.clients[cid] = node
            elif payload.get("op") == "cdel":
                if self.clients.get(cid) == node:
                    del self.clients[cid]
            return
        try:
            self.broker.apply_config(payload["path"], payload["value"])
        except Exception:
            log.exception("raft conf entry %d failed (%r)", index,
                          payload.get("path"))

    def _raft_ds_apply(self, index: int, payload: Dict) -> None:
        """Committed DS entries land in EVERY member's replica store
        (the origin included — its replica survives its own restart),
        so an acked write is readable wherever the client reconnects."""
        kind = payload.get("kind")
        if kind == "batch":
            for entry in payload.get("entries", ()):
                self._raft_ds_apply(index, entry)
            return
        if kind == "orphans":
            self.replicas.add_orphans(payload.get("messages", ()))
            return
        cid = payload.get("clientid", "")
        if kind == "ckpt":
            self.replicas.store_checkpoint(cid, payload.get("state", {}))
        elif kind == "msgs":
            self.replicas.append_messages(
                cid, payload.get("messages", [])
            )
        elif kind == "drop":
            self.replicas.drop(cid)

    def _track_quorum(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._quorum_inflight.add(task)
        task.add_done_callback(self._quorum_inflight.discard)
        self._fwd_tasks.add(task)
        task.add_done_callback(self._fwd_done)
        return task

    def _fwd_done(self, task: asyncio.Task) -> None:
        self._fwd_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            self.broker.metrics.inc("messages.forward.failed")
            log.error(
                "%s: forward task crashed", self.name,
                exc_info=task.exception(),
            )

    async def _forward_sync_drain(self, timeout: float = 5.0) -> None:
        """Raft-mode forward flush: each target must CONFIRM it
        committed the resulting DS entries; a dead target's window is
        quorum-stored as orphans instead (by topic; restores match
        them against session filters).  A failed leg RE-QUEUES its
        messages before raising, so a barrier retry flushes them again
        instead of acking a window that was never made durable."""
        pending, self._pending_fwd = self._pending_fwd, {}
        if not pending:
            return

        async def fwd(node: str, msgs: List[Message]) -> None:
            wires = [msg_to_wire(m) for m in msgs]
            reply = await self.transport.call(node, {
                "type": "forward_sync", "msgs": wires,
            }, timeout=timeout)
            spans = _fwd_spans(msgs)
            if reply and reply.get("ok"):
                for span in spans:
                    span.end(True)
                return
            # close the forward spans on the retry/orphan path BEFORE
            # the quorum submit (which may raise and re-queue): the
            # publisher-side trace must close even when the target died
            # mid-window.  PendingForward.end is once-only, so the
            # re-queued retry cannot double-emit.
            for span in spans:
                span.end(False, "no ack; quorum-orphaned")
            # orphaned wires bypass the peer's ingress strip (they
            # restore straight into session mqueues), so the trace
            # carrier must come OFF here or it reaches a subscriber's
            # wire on replay
            strip_wire_trace_ctx(wires)
            self.broker.metrics.inc("messages.forward.failed",
                                    len(msgs))
            await self.raft_ds.submit(
                {"kind": "orphans", "messages": wires}, timeout=timeout
            )

        items = list(pending.items())
        results = await asyncio.gather(
            *(fwd(n, m) for n, m in items), return_exceptions=True
        )
        first_err = None
        for (node, msgs), res in zip(items, results):
            if isinstance(res, BaseException):
                self._pending_fwd.setdefault(node, [])[:0] = msgs
                first_err = first_err or res
        if first_err is not None:
            raise first_err

    async def quorum_barrier(self, timeout: float = 5.0) -> None:
        """The PUBACK gate in raft mode: resolves once (a) every
        cross-node forward buffered by this window is either
        CONFIRMED-COMMITTED by its target node or quorum-stored as an
        orphan (target dead mid-window — the exact race a leader kill
        opens), (b) this node's own DS entries are committed, and (c)
        any quorum work the background flush loop already has in
        flight for earlier parts of the window has resolved.  After
        this, an acked QoS1 publish destined for any persistent
        session survives any single node failure."""
        if self.raft_ds is None:
            return
        for _ in range(3):
            inflight = list(self._quorum_inflight)
            # the loop-exit emptiness checks below are convergence
            # tests, not decisions acted on: both drains re-snapshot
            # their pending sets internally, and a fill racing the
            # check just means one more bounded round
            # brokerlint: ignore[RACE801]
            await self._forward_sync_drain(timeout)
            # brokerlint: ignore[RACE801]
            await self.flush_ds(timeout)
            errs = []
            if inflight:
                results = await asyncio.gather(
                    *inflight, return_exceptions=True
                )
                errs = [
                    r for r in results
                    if isinstance(r, Exception)
                ]
            # a failed in-flight flush RE-QUEUED its entries: another
            # round flushes them; acking despite an error would claim
            # durability for entries that never committed
            if not errs and not self._pending_repl_raft \
                    and not self._pending_fwd:
                return
            if errs and not self._pending_repl_raft \
                    and not self._pending_fwd:
                raise errs[0]
        raise TimeoutError("quorum barrier did not settle")

    async def _handle_forward_sync(self, peer: str, obj: Dict) -> Dict:
        """Sync forward (raft mode): dispatch AND commit the resulting
        DS entries before replying — the origin's PUBACK waits on this
        reply."""
        try:
            msgs = [msg_from_wire(w) for w in obj.get("msgs", ())]
            self.broker.metrics.inc(
                "messages.forward.received", len(msgs)
            )
            self.broker.dispatch_forwarded_many(msgs)
            await self.flush_ds()
            return {"ok": True}
        except Exception:
            log.exception("sync forward from %s failed", peer)
            return {"ok": False}

    async def flush_ds(self, timeout: float = 5.0) -> None:
        """Quorum barrier for the DS entries buffered so far: returns
        once every one of them is COMMITTED (majority-replicated).
        The publish batcher awaits this before resolving QoS1 futures,
        so a PUBACK implies the persistent-session copy survives any
        single node failure — the reference's store_batch-through-ra
        ack semantics (emqx_ds_replication_layer.erl)."""
        if self.raft_ds is None:
            return
        pending, self._pending_repl_raft = self._pending_repl_raft, []
        if not pending:
            return
        try:
            # ONE log entry per flush window: a single quorum
            # round-trip covers the whole batch and preserves
            # per-client ordering (ckpt-then-msgs) within it
            await self.raft_ds.submit(
                {"kind": "batch", "entries": pending}, timeout=timeout
            )
        except Exception:
            # an un-acked window's entries go back for a later flush
            # (leadership churn); the caller's raise keeps the PUBACK
            # withheld, so there is no false durability claim
            self._pending_repl_raft = pending + self._pending_repl_raft
            raise

    def discard_remote(self, clientid: str) -> None:
        """Fire-and-forget kick of a duplicate session on its owning
        node (clean_start reconnect elsewhere: cluster-wide clientid
        uniqueness without a state transfer)."""
        owner = self.remote_owner(clientid)
        if owner is None:
            return
        loop = asyncio.get_running_loop()
        task = loop.create_task(
            self.transport.cast(
                owner, {"type": "client_discard", "clientid": clientid}
            )
        )
        self._fwd_tasks.add(task)
        task.add_done_callback(self._fwd_tasks.discard)

    async def _handle_client_discard(self, peer: str, obj: Dict) -> None:
        self.broker.cm.kick(obj.get("clientid", ""))

    async def takeover(self, clientid: str) -> Optional[Dict]:
        """Fetch (and migrate away) the session owned by a peer — the
        requester side of emqx_cm's takeover_session_begin/end
        (emqx_cm.erl:314-317) over the cluster transport."""
        owner = self.remote_owner(clientid)
        if owner is None:
            return None
        reply = await self.transport.call(
            owner, {"type": "takeover", "clientid": clientid}
        )
        if reply is None:
            return None
        self.broker.metrics.inc("session.takeover.requested")
        return reply.get("state")

    async def _handle_takeover(self, peer: str, obj: Dict) -> Dict:
        state = self.broker.export_session(obj.get("clientid", ""))
        return {"state": state}

    # --------------------------------------------------- node inventory

    async def _handle_node_info(self, peer: str, obj: Dict) -> Dict:
        return {"info": self.broker.node_info()}

    async def fetch_node_infos(self, timeout: float = 2.0) -> List[Dict]:
        """Every alive peer's `Broker.node_info` row, gathered
        concurrently — the merged ``GET /api/v5/nodes`` view a
        multicore pool serves from ANY worker's api port (each row
        carries that worker's own olp level, durability surface, and
        match-service attachment)."""
        peers = sorted(self.peers_alive())
        if not peers:
            return []

        async def one(p: str) -> Optional[Dict]:
            try:
                reply = await self.transport.call(
                    p, {"type": "node_info"}, timeout=timeout
                )
            except Exception:
                return None
            return (reply or {}).get("info")

        rows = await asyncio.gather(*(one(p) for p in peers))
        return [r for r in rows if r]

    # ----------------------------------------------------- forwarding

    def match_remote(self, topics: List[str]) -> List[set]:
        """Nodes (other than self) with matching routes, per topic.

        Sharded mode scatter-gathers the window across the shard
        owners.  Called from the batcher's executor thread, it blocks
        that thread on the cluster round-trip (the window is pipelined
        anyway); called ON the event loop (rare sync publishes: wills,
        $SYS), it cannot wait for network — it floods the window to
        all alive peers, which is correct (receivers match locally
        before dispatch) just not minimal."""
        if self.shard is None:
            return self.routes.match_nodes(topics, exclude=self.name)
        try:
            asyncio.get_running_loop()
            on_loop = True
        except RuntimeError:
            on_loop = False
        if on_loop or self._loop is None:
            self.shard.stats["flood"] += 1
            alive = set(self.peers_alive())
            return [set(alive) for _ in topics]
        fut = asyncio.run_coroutine_threadsafe(
            self.shard.match_scatter(list(topics)), self._loop
        )
        try:
            return fut.result(timeout=5.0)
        except Exception:
            log.exception("%s: shard scatter failed; flooding", self.name)
            self.shard.stats["flood"] += 1
            alive = set(self.peers_alive())
            return [set(alive) for _ in topics]

    def forward(self, msg: Message, nodes: set) -> None:
        """Buffer the message per destination; the flush loop coalesces
        each window into ONE binary frame per peer (payload bytes raw)
        — the batched, re-encode-free analogue of async forward casts
        (rpc.mode=async, emqx_broker.erl:387-391; VERDICT r2 weak #7).

        A SAMPLED message buffers a traced copy per peer instead: a
        ``message.forward`` span opens here and its id rides the
        copy's user properties across the wire, so the peer's
        forwarded-dispatch span parents to it — one connected trace
        per hop.  The span is closed by whichever flush path learns
        the outcome; unsampled messages buffer the original object
        untouched."""
        lifecycle = getattr(self.broker, "lifecycle", None)
        ctx = (
            getattr(msg, "_trace_ctx", None)
            if lifecycle is not None and lifecycle.active else None
        )
        for node in nodes:
            if node in self._down:
                continue
            m = (
                lifecycle.forward_copy(msg, ctx, node)
                if ctx is not None else msg
            )
            self._pending_fwd.setdefault(node, []).append(m)
            if len(self._pending_fwd[node]) >= self.flush_max:
                self._flush_wakeup.set()

    # -- sender side: sequenced frames, bounded replay buffer, breaker

    def _fwd_state(self, node: str) -> _FwdPeer:
        st = self._fwd_out.get(node)
        if st is None:
            st = self._fwd_out[node] = _FwdPeer()
        return st

    async def _flush_forwards(self) -> None:
        """Flush buffered windows as ONE sequenced frame per peer.

        Unlike the old fire-and-forget cast, each frame enters the
        peer's in-flight replay buffer and stays there until the peer
        acks its (epoch, seq) — link loss, a dead peer, or a dropped
        datagram only delays it.  Overflow sheds QoS0-only frames
        first (counted ``messages.forward.dropped``); an open breaker
        parks frames for the probe loop instead of burning sends."""
        from .wire import encode_window

        pending, self._pending_fwd = self._pending_fwd, {}
        loop = asyncio.get_running_loop()
        fl = getattr(self.broker, "flight", None)
        for node, msgs in pending.items():
            st = self._fwd_state(node)
            self._fwd_make_room(node, st)
            st.seq += 1
            seq = st.seq
            if fl is not None:
                fl.record(_EV_FWD, float(len(msgs)), float(seq))
            max_qos = max((m.qos for m in msgs), default=0)
            base = next(iter(st.inflight), seq)
            blob = encode_window(self._epoch, seq, base, msgs)
            frame = _FwdFrame(seq, blob, len(msgs), max_qos,
                              _fwd_spans(msgs))
            st.inflight[seq] = frame
            if st.breaker_open:
                continue  # the probe loop owns sends while open
            self._spawn_frame_send(node, st, frame)

    def _fwd_make_room(self, node: str, st: _FwdPeer) -> None:
        """Shed policy for a full replay buffer: QoS0-only frames go
        first (their contract allows loss), then the oldest frame —
        bounded memory beats an unbounded queue to a dead peer."""
        while len(st.inflight) >= self.fwd_inflight_max:
            victim = None
            for frame in st.inflight.values():
                if frame.max_qos == 0:
                    victim = frame
                    break
            if victim is None:
                victim = next(iter(st.inflight.values()))
            del st.inflight[victim.seq]
            self._fwd_shed(node, st, victim, "replay buffer overflow")

    def _fwd_shed(self, node: str, st: _FwdPeer, frame: _FwdFrame,
                  why: str) -> None:
        st.shed += frame.n
        self.broker.metrics.inc("messages.forward.dropped", frame.n)
        if frame.spans:
            for span in frame.spans:
                span.end(False, why)
        if frame.max_qos > 0:
            log.warning(
                "%s: shed QoS%d forward frame seq=%d (%d msgs) for "
                "%s: %s", self.name, frame.max_qos, frame.seq,
                frame.n, node, why,
            )

    def _spawn_frame_send(self, node: str, st: _FwdPeer,
                          frame: _FwdFrame) -> None:
        task = asyncio.get_running_loop().create_task(
            self._send_frame(node, st, frame)
        )
        self._fwd_tasks.add(task)
        task.add_done_callback(
            lambda t, f=frame: self._fwd_send_done(t, f)
        )

    def _fwd_send_done(self, task: asyncio.Task,
                       frame: _FwdFrame) -> None:
        self._fwd_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            if frame.retx == 0:
                # same units + same once-per-frame guard as the
                # ok=False path in _send_frame: message count, first
                # failure only
                self.broker.metrics.inc(
                    "messages.forward.failed", frame.n
                )
            log.error(
                "%s: forward task crashed", self.name,
                exc_info=task.exception(),
            )
            # arm the retransmit timer: a send that died BEFORE the
            # cast returned never set sent_at, and a None timestamp
            # would park the frame forever
            if frame.sent_at is None:
                frame.sent_at = time.monotonic()
            # the frame's spans CLOSE here (PR 8 invariant: a dropped
            # leg still yields a closed span; PendingForward.end is
            # once-only, so the frame's eventual retransmit-ack close
            # becomes a no-op).  The frame itself stays in the replay
            # buffer — a crashed send never loses the window.
            if frame.spans:
                for span in frame.spans:
                    span.end(False, "forward task crashed")

    async def _send_frame(self, node: str, st: _FwdPeer,
                          frame: _FwdFrame) -> None:
        if frame.seq not in st.inflight:
            return  # acked or shed while this send was queued
        ok = await self.transport.cast_bin(
            node, "forward_batch", frame.blob
        )
        now = time.monotonic()
        if ok:
            # the ack timer starts at the SEND, so a lost ack is
            # detected by the retx loop, not trusted forever
            frame.sent_at = now
            return
        frame.sent_at = now  # failed send backs off like a lost ack
        if frame.retx == 0:
            # count each frame's messages failed ONCE — a breaker
            # probe or retransmit failing again must not re-inflate
            # the counter for messages that will still be delivered
            # on recovery
            self.broker.metrics.inc(
                "messages.forward.failed", frame.n
            )
        self._fwd_failure(node, st)

    def _fwd_failure(self, node: str, st: _FwdPeer) -> None:
        """One delivery failure signal (failed send or ack timeout):
        advances closed -> suspect -> open, the PR 1 breaker shape."""
        st.fail_streak += 1
        if not st.suspect and st.fail_streak >= \
                self.fwd_suspect_threshold:
            st.suspect = True
            log.warning("%s: peer %s forward link SUSPECT after %d "
                        "failures", self.name, node, st.fail_streak)
        if not st.breaker_open and st.fail_streak >= \
                self.fwd_breaker_threshold:
            st.breaker_open = True
            st.next_probe = time.monotonic() + self.fwd_probe_interval
            self.broker.metrics.inc("cluster.forward.breaker.open")
            self.broker.alarms.activate(
                f"cluster_forward_breaker_{node}",
                details={"peer": node,
                         "unacked_frames": len(st.inflight),
                         "failures": st.fail_streak},
                message=f"forward breaker OPEN for peer {node}: "
                        f"sends parked, probing every "
                        f"{self.fwd_probe_interval}s",
            )
            log.warning(
                "%s: forward breaker OPEN for %s (%d consecutive "
                "failures, %d frames parked)", self.name, node,
                st.fail_streak, len(st.inflight),
            )

    def _fwd_recover(self, node: str, st: _FwdPeer) -> None:
        """An ack arrived: the link works — reset the failure ladder
        and, if the breaker was open, re-close it and resume."""
        st.fail_streak = 0
        st.suspect = False
        if st.breaker_open:
            st.breaker_open = False
            self.broker.alarms.deactivate(
                f"cluster_forward_breaker_{node}"
            )
            log.info("%s: forward breaker for %s re-CLOSED; "
                     "%d frames to replay", self.name, node,
                     len(st.inflight))
            if st.inflight:
                self._spawn_resend(node, st)

    async def _fwd_retx_loop(self) -> None:
        """Retransmission driver: exponential backoff + jitter on the
        oldest unacked frame's age; an OPEN breaker downgrades to a
        slow single-frame probe (the background probe that re-closes
        it, same shape as the PR 1 device breaker's)."""
        tick = max(0.01, min(self.fwd_ack_timeout / 4, 0.05))
        while True:
            await asyncio.sleep(tick)
            now = time.monotonic()
            for node, st in list(self._fwd_out.items()):
                if node not in self._peers:
                    # departed peer: a retained buffer would leak
                    # forever (forget_peer is the explicit path; this
                    # is the defensive reap)
                    self._reap_fwd_state(node)
                    continue
                if not st.inflight:
                    continue
                if st.breaker_open:
                    if now >= st.next_probe:
                        st.next_probe = now + self.fwd_probe_interval
                        frame = next(iter(st.inflight.values()))
                        frame.retx += 1
                        self.broker.metrics.inc("messages.forward.retx")
                        self._spawn_frame_send(node, st, frame)
                    continue
                oldest = next(iter(st.inflight.values()))
                if oldest.sent_at is None:
                    continue  # initial send still queued
                backoff = min(
                    self.fwd_ack_timeout * (2 ** min(oldest.retx, 6)),
                    self.fwd_backoff_max,
                )
                # jitter: +-20%, so a mass-reconnect of peers does not
                # synchronize its retransmit bursts
                backoff *= 0.8 + 0.4 * self._fwd_rng.random()
                if now - oldest.sent_at < backoff:
                    continue
                self._fwd_failure(node, st)
                if st.breaker_open:
                    continue
                ts_ns = time.time_ns()
                for frame in st.inflight.values():
                    frame.retx += 1
                    if frame.spans:
                        for span in frame.spans:
                            span.span["events"].append({
                                "name": "forward.retransmit",
                                "ts_ns": ts_ns,
                                "attrs": {"retx": frame.retx,
                                          "seq": frame.seq},
                            })
                self.broker.metrics.inc("messages.forward.retx",
                                        len(st.inflight))
                self._spawn_resend(node, st)

    def _spawn_resend(self, node: str, st: _FwdPeer) -> None:
        task = asyncio.get_running_loop().create_task(
            self._resend_unacked(node, st)
        )
        self._fwd_tasks.add(task)
        task.add_done_callback(self._fwd_done)  # crash = logged

    async def _resend_unacked(self, node: str, st: _FwdPeer) -> None:
        """Retransmit every unacked frame in seq order (the receiver's
        dedup window absorbs any that actually arrived)."""
        for seq in list(st.inflight):
            frame = st.inflight.get(seq)
            if frame is None:
                continue  # acked while we were resending
            ok = await self.transport.cast_bin(
                node, "forward_batch", frame.blob
            )
            frame.sent_at = time.monotonic()
            if not ok:
                self._fwd_failure(node, st)
                return  # link is down; backoff/breaker takes over

    async def _handle_fwd_ack(self, peer: str, obj: Dict) -> None:
        """Ack from a forward target: release the frames, close their
        spans with the measured ack latency, and reset the peer's
        failure ladder (re-closing an open breaker)."""
        if obj.get("epoch") != self._epoch:
            return  # ack for a previous incarnation's stream
        node = obj.get("node", peer)
        st = self._fwd_out.get(node)
        if st is None:
            return
        now = time.monotonic()
        ts_ns = time.time_ns()
        for seq in obj.get("seqs", ()):
            frame = st.inflight.pop(seq, None)
            if frame is None:
                continue  # re-ack of an already-released frame
            st.acked += 1
            if frame.spans:
                ack_ms = (
                    round((now - frame.sent_at) * 1000.0, 3)
                    if frame.sent_at is not None else 0.0
                )
                for span in frame.spans:
                    span.span["events"].append({
                        "name": "forward.acked",
                        "ts_ns": ts_ns,
                        "attrs": {"ack_ms": ack_ms,
                                  "retx": frame.retx},
                    })
                    span.span["attrs"]["ack_ms"] = ack_ms
                    span.span["attrs"]["retx"] = frame.retx
                    span.end(True)
        self._fwd_recover(node, st)

    def _reap_fwd_state(self, node: str) -> None:
        """Drop ALL forward state for a departed peer: pending
        buffers, the replay buffer (shed + counted), receiver dedup
        state, and any open breaker alarm."""
        pending = self._pending_fwd.pop(node, None)
        if pending:
            self.broker.metrics.inc(
                "messages.forward.dropped", len(pending)
            )
            for span in _fwd_spans(pending):
                span.end(False, "peer removed")
        st = self._fwd_out.pop(node, None)
        if st is not None:
            for frame in list(st.inflight.values()):
                self._fwd_shed(node, st, frame, "peer removed")
            st.inflight.clear()
            if st.breaker_open:
                self.broker.alarms.deactivate(
                    f"cluster_forward_breaker_{node}"
                )
        self._fwd_in.pop(node, None)

    def forget_peer(self, node: str) -> None:
        """Remove a peer from membership PERMANENTLY (it left the
        cluster, as opposed to ``_node_down``'s it-may-return): its
        routes, client claims, links, and every forward buffer are
        reaped — a departed peer must not retain replay state
        forever."""
        if node in self._peers or node in self._fwd_out \
                or node in self._pending_fwd:
            self._peers.pop(node, None)
            self._peer_roles.pop(node, None)
            self._last_seen.pop(node, None)
            self._down.discard(node)
            self._synced.discard(node)
            self.routes.purge_node(node)
            for cid, n in list(self.clients.items()):
                if n == node:
                    del self.clients[cid]
            self.transport.drop_peer(node)
            self._reap_fwd_state(node)
            log.info("%s: peer %s removed from membership", self.name,
                     node)

    def forward_stats(self) -> Dict[str, Any]:
        """Reliability-layer introspection (mgmt/ctl surfaces)."""
        peers = {}
        for node, st in self._fwd_out.items():
            peers[node] = {
                "unacked_frames": len(st.inflight),
                "unacked_msgs": sum(
                    f.n for f in st.inflight.values()
                ),
                "next_seq": st.seq + 1,
                "acked_frames": st.acked,
                "shed_msgs": st.shed,
                "fail_streak": st.fail_streak,
                "breaker": (
                    "open" if st.breaker_open
                    else "suspect" if st.suspect else "closed"
                ),
            }
        return {
            "mode": self.transport.transport_mode,
            "quic_demotions": self.transport.stats["quic_demotions"],
            "peers": peers,
        }

    # -- receiver side: dedup window + ack

    async def _handle_forward_batch(self, peer: str, obj: Dict) -> None:
        from .wire import decode_window

        try:
            epoch, seq, base, _max_qos, msgs = decode_window(
                obj["_bin"]
            )
        except Exception:
            # a malformed frame must not crash the serve loop
            log.exception("undecodable forward batch from %s", peer)
            return
        st = self._fwd_in.get(peer)
        if st is not None and epoch < st[0]:
            # reordered straggler from the origin's PREVIOUS
            # incarnation: resetting on it would wipe the live
            # epoch's dedup state (re-dispatching every in-flight
            # retransmit) — drop it, un-acked; that sender is gone
            return
        if st is None or epoch > st[0]:
            # first frame, or the origin restarted (newer epoch):
            # fresh dedup window — the old incarnation's seqs are
            # garbage
            st = self._fwd_in[peer] = [epoch, 0, set()]
        if base - 1 > st[1]:
            # the origin will never (re)send below `base`: holes left
            # by its overflow shedding must not wedge the floor
            st[1] = base - 1
            floor = st[1]
            st[2] = {s for s in st[2] if s > floor}
        if seq <= st[1] or seq in st[2]:
            # retransmit duplicate: the ack the origin missed is
            # re-sent, the window is NOT re-dispatched
            self.broker.metrics.inc("messages.forward.dup", len(msgs))
        elif len(st[2]) >= 65536 and seq != st[1] + 1:
            # pathological reordering bound: REFUSE the frame (no
            # dispatch, no ack, no state) instead of force-advancing
            # the floor — a forced floor would ack the gap frames
            # below it as "duplicates" without ever dispatching them,
            # which is silent QoS>=1 loss.  Unacked, the origin
            # retransmits (lowest seq first), the gaps fill, and the
            # floor advances through the contiguity walk — bounded
            # memory without breaking at-least-once.  The gap frame
            # itself (seq == floor+1) is ALWAYS admitted: it advances
            # the floor immediately and drains the set, so refusal
            # can't wedge the stream it is protecting.
            log.warning(
                "%s: forward dedup window for %s at capacity "
                "(floor=%d); refusing seq=%d until gaps fill",
                self.name, peer, st[1], seq,
            )
            return
        else:
            self.broker.metrics.inc(
                "messages.forward.received", len(msgs)
            )
            # dispatch-only: hooks/retain/rules already ran on the
            # origin node (the reference's forward lands in dispatch/2
            # directly, emqx_broker.erl:408-420); one batched match
            # step per frame
            try:
                self.broker.dispatch_forwarded_many(msgs)
                dur = self.broker.durable
                if (
                    dur is not None
                    and dur.fsync_mode == "always"
                    and dur.gate.dirty
                ):
                    # acked-to-origin means durable HERE too: on this
                    # ack the origin drops its replay copy, so a
                    # captured forwarded message must hit disk first
                    # (the cluster hop of the group-commit contract).
                    # BOUNDED wait: this handler runs in the per-peer
                    # serial pump, so a disk stalled in the gate's
                    # retry loop must not head-of-line-block the
                    # peer's heartbeats/acks forever — on timeout the
                    # frame stays un-acked/un-deduped and the origin's
                    # retransmit retries once the disk recovers.
                    await asyncio.wait_for(
                        dur.wait_durable(), timeout=2.0
                    )
            except asyncio.TimeoutError:
                return
            except Exception:
                # store/dispatch failure: no ack, no dedup state — the
                # retransmit re-delivers (at-least-once, never a
                # silently-dropped acked window)
                log.exception(
                    "forwarded window from %s not acked", peer
                )
                return
            st[2].add(seq)
            while st[1] + 1 in st[2]:
                st[1] += 1
                st[2].discard(st[1])
        await self._send_fwd_ack(peer, epoch, [seq])

    async def _send_fwd_ack(self, peer: str, epoch: int,
                            seqs: List[int]) -> None:
        """Ack path seam: ``drop``/``error`` lose the ack — the
        origin retransmits and the dedup window absorbs the
        duplicate, which is exactly the at-least-once contract."""
        try:
            act = await failpoints.evaluate_async(
                "cluster.forward.ack", key=f"{self.name}->{peer}"
            )
        except failpoints.FailpointError:
            return
        if act == "drop":
            return
        await self.transport.cast(peer, {
            "type": "fwd_ack", "node": self.name,
            "epoch": epoch, "seqs": seqs,
        })

    # ----------------------------------------------------- membership

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_interval)
            obj = {
                "type": "heartbeat",
                "node": self.name,
                "role": self.role,
                "listen": [self.transport.bind, self.transport.port],
            }
            # bound each cast so one blackholed peer can't stall the
            # loop (and thereby starve heartbeats to healthy peers)
            await asyncio.gather(
                *(
                    asyncio.wait_for(
                        self.transport.cast(p, obj),
                        self.heartbeat_interval * 4,
                    )
                    for p in self._peers
                ),
                return_exceptions=True,
            )
            self.replicas.purge_expired()
            now = time.monotonic()
            for p, seen in list(self._last_seen.items()):
                if p in self._down:
                    continue
                if now - seen > self.down_after:
                    self._node_down(p)
            # retry any initial sync that failed (peer was not yet up)
            for p in self.peers_alive():
                if p not in self._synced:
                    # the membership/liveness checks go stale across
                    # each awaited sync, but _sync_with is an
                    # idempotent full-state resend — a duplicate or
                    # late sync is harmless
                    # brokerlint: ignore[RACE801]
                    await self._sync_with(p)

    async def _handle_heartbeat(self, peer: str, obj: Dict) -> None:
        node = obj.get("node", peer)
        self._peer_roles[node] = obj.get("role", "core")
        self._learn_peer(node, obj.get("listen"))
        if node not in self._peers:
            return
        came_back = node in self._down
        self._mark_alive(node)
        if came_back:
            log.info("%s: node %s is back, resyncing routes", self.name, node)
            # membership was checked before _mark_alive; a concurrent
            # removal just makes this an extra idempotent sync
            # brokerlint: ignore[RACE801]
            await self._sync_with(node)
            # unacked forwarded windows replay NOW: the restarted (or
            # re-reachable) peer gets every frame it never acked —
            # the reconnect half of at-least-once forwarding
            st = self._fwd_out.get(node)
            if st is not None and st.inflight:
                if st.breaker_open:
                    st.next_probe = 0.0  # probe on the next tick
                else:
                    self._spawn_resend(node, st)

    async def _handle_conn_count(self, peer: str, obj: Dict) -> Dict:
        """Live connection census for the rebalance planner."""
        cm = self.broker.cm
        return {"count": sum(
            1 for cid in cm.clients() if cm.connected(cid)
        )}

    async def _handle_rebalance_shed(self, peer: str, obj: Dict) -> None:
        """A coordinator asked this donor to shed its excess (or to
        stop a shed it started earlier)."""
        if obj.get("stop"):
            await self.broker.rebalance.stop_local()
            return
        self.broker.rebalance.start_shed(
            int(obj.get("count", 0)), int(obj.get("rate", 50))
        )

    async def _handle_session_purge(self, peer: str, obj: Dict) -> None:
        """Cluster-wide detached-session purge fan-out (start/stop)."""
        if obj.get("stop"):
            await self.broker.purger.stop_purge()
            return
        try:
            await self.broker.purger.start_purge(
                int(obj.get("rate", 500))
            )
        except RuntimeError:
            log.info("purge refused: eviction busy on this node")

    def _mark_alive(self, node: str) -> None:
        self._last_seen[node] = time.monotonic()
        self._down.discard(node)

    def _node_down(self, node: str) -> None:
        """Declare a peer dead: purge its replica routes so publishes
        stop forwarding into the void.  In raft mode a deterministic
        survivor then ADOPTS each of the dead node's quorum-replicated
        detached sessions (the reference's shard failover / replica
        re-election role): the adopter re-advertises the session's
        filters, so publishes during the owner-dead window keep
        matching and keep accumulating — without this they would
        black-hole after the purge despite being PUBACKed."""
        self._down.add(node)
        self._synced.discard(node)
        purged = self.routes.purge_node(node)
        if self.shard is not None:
            # drop the dead node's entries from OUR shard, and
            # re-announce local filters — ownership reshuffled
            purged += self.shard.table.purge_node(node)
            self.shard.on_membership_change()
        orphan_cids = [
            cid for cid, n in self.clients.items() if n == node
        ]
        for cid in orphan_cids:
            del self.clients[cid]
        self.transport.drop_peer(node)
        self.broker.metrics.inc("cluster.nodes.down")
        self.broker.hooks.run("node.down", node)
        log.warning(
            "%s: node %s down, purged %d routes", self.name, node, purged
        )
        if self.raft_ds is not None:
            self._adopt_dead_sessions(node, orphan_cids)

    def _adopt_dead_sessions(self, node: str,
                             orphan_cids: List[str]) -> None:
        survivors = sorted(self.peers_alive() + [self.name])
        adopted = 0
        for cid in orphan_cids:
            if rendezvous_pick(cid, survivors, 1)[0] != self.name:
                continue  # another survivor adopts this one
            state = self.replicas.peek(cid)
            if state is None:
                continue
            try:
                self.broker.adopt_orphan_session(
                    cid, state, float(state.get("expiry", 0.0))
                )
                # re-checkpoint through the quorum under the NEW home
                # so the adoption itself survives further failures
                self.replicate_checkpoint(
                    cid, state.get("subs", {}),
                    float(state.get("expiry", 0.0)),
                    list(state.get("queued", [])),
                )
                adopted += 1
            except Exception:
                log.exception("%s: adopting session %r failed",
                              self.name, cid)
        if adopted:
            log.info("%s: adopted %d detached sessions from dead %s",
                     self.name, adopted, node)

    # ------------------------------------------------------ introspection

    def info(self) -> Dict[str, Any]:
        return {
            "node": self.name,
            "peers": sorted(self._peers),
            "alive": sorted(self.peers_alive()),
            "down": sorted(self._down),
            "routes": len(self.routes),
            "forward": self.forward_stats(),
        }
