"""Cluster layer: membership, route replication, publish forwarding.

The reference clusters through three mechanisms (SURVEY §5.8): mria
table replication (every node holds all routes), gen_rpc forwarding
(emqx_broker.erl:387-406), and ekka membership/autoheal.  Here:

  * `transport`  — length-prefixed JSON RPC over asyncio TCP between
    nodes (the gen_rpc analogue, with a BPAPI-style proto version).
  * `routes`     — full-replica cluster route table (filter -> nodes),
    wildcard-indexed by its own MatchEngine so remote routing rides
    the same TPU match step as local routing.
  * `node`       — ClusterNode: wires a Broker into the cluster
    (route-delta broadcast, forward, heartbeat membership, dead-node
    route purge — emqx_router_helper:cleanup_routes).
"""

from .node import ClusterNode
from .routes import ClusterRouteTable
from .transport import NodeTransport

__all__ = ["ClusterNode", "ClusterRouteTable", "NodeTransport"]
