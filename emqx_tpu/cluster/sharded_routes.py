"""Cluster-SHARDED route index: the wildcard set partitioned across
nodes instead of fully replicated to each.

The reference replicates the whole route table to every node
(/root/reference/apps/emqx/src/emqx_router.erl:133-162 via mria), so
each node's RAM and index-build time grow with the CLUSTER's total
subscription count — the scale cap VERDICT r4 called out (10M subs x
N nodes = N full copies, N full 26 s builds).  This mode divides the
cluster's filter set by rendezvous hash: each node OWNS ~1/N of the
filters and indexes only those in its MatchEngine (the same batched
device step), so adding nodes divides both the per-node index and the
build.

Data flow:
  * a node whose local client subscribes to F sends a shard op to
    owner(F); the owner records (F -> origin node) in its shard table;
  * a publish window scatters its topics to every alive peer in ONE
    ``shard_match`` call each; every shard matches its partition and
    returns per-topic subscriber-node sets; the publisher unions them
    (the "match locally, union over the forward wire" plan,
    SURVEY §5.8) and forwards to those nodes as usual;
  * membership change (join/death/recovery) triggers a RESYNC: every
    node re-announces its local filters to the current owners, and
    purges owned entries whose ownership moved away.  Until resyncs
    land, scatter failures degrade to FLOODING the window to all
    alive peers — receivers match locally before dispatch, so
    flooding is always correct, just not minimal.

Consistency guard: ops carry a per-origin (epoch, seq) stream and the
resync snapshot carries the seq it was cut at, mirroring the full-
replica path's snapshot-vs-racing-casts reconciliation
(cluster/node.py _apply_snapshot).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Dict, List, Sequence, Set

from ..ds.replication import rendezvous_pick
from .routes import ClusterRouteTable

log = logging.getLogger("emqx_tpu.cluster.shard")


class ShardedRouteIndex:
    def __init__(self, node) -> None:
        self.node = node
        # owned partition only: filter -> {subscriber nodes}
        self.table = ClusterRouteTable()
        self._seq = 0
        self._pending: Dict[str, List] = {}  # owner -> [(seq, op, flt)]
        # per-origin op-stream state (epoch invalidates across restart)
        self._origin_epoch: Dict[str, int] = {}
        self._origin_seq: Dict[str, int] = {}
        self._origin_log: Dict[str, deque] = {}
        self.resync_due = False
        self.stats = {"scatter": 0, "flood": 0, "resync": 0}
        # filters whose ownership moved AWAY from this node, with the
        # time we first noticed: purged only after MOVED_GRACE
        self._moved: Dict[str, float] = {}
        self.MOVED_GRACE = 10.0

    # ------------------------------------------------------ ownership

    def _alive(self) -> List[str]:
        return sorted(self.node.peers_alive() + [self.node.name])

    def owner_of(self, flt: str) -> str:
        return rendezvous_pick(flt, self._alive(), 1)[0]

    # ------------------------------------------------------ local ops

    def local_op(self, op: str, flt: str) -> None:
        """A local subscriber created/destroyed the route for `flt`:
        tell the filter's shard owner."""
        self._seq += 1
        owner = self.owner_of(flt)
        if owner == self.node.name:
            self._apply(op, flt, self.node.name, self._seq,
                        self.node._epoch)
        else:
            self._pending.setdefault(owner, []).append(
                (self._seq, op, flt)
            )
            if len(self._pending[owner]) >= self.node.flush_max:
                self.node._flush_wakeup.set()

    @property
    def has_work(self) -> bool:
        return bool(self._pending) or self.resync_due

    async def flush(self) -> None:
        """Drain pending ops (one cast per owner) and any due resync;
        driven by the ClusterNode flush loop."""
        if self._pending:
            pending, self._pending = self._pending, {}
            for owner, ops in pending.items():
                ok = await self.node.transport.cast(owner, {
                    "type": "shard_ops",
                    "node": self.node.name,
                    "epoch": self.node._epoch,
                    "ops": ops,
                })
                if not ok:
                    # owner unreachable: a membership change will
                    # follow and the resync re-announces everything
                    self.resync_due = True
        if self.resync_due:
            self.resync_due = False
            try:
                await self.resync()
            except Exception:
                log.exception("%s: shard resync failed", self.node.name)
                self.resync_due = True

    # --------------------------------------------------- owner side

    def _check_epoch(self, origin: str, epoch: int) -> None:
        if self._origin_epoch.get(origin) != epoch:
            self._origin_epoch[origin] = epoch
            self._origin_seq[origin] = 0
            self._origin_log[origin] = deque(maxlen=8192)

    def _apply(self, op: str, flt: str, origin: str, seq: int,
               epoch: int) -> None:
        self._check_epoch(origin, epoch)
        if seq <= self._origin_seq.get(origin, 0):
            return  # already reflected by a resync snapshot
        if op == "add":
            self.table.add_route(flt, origin)
        else:
            self.table.delete_route(flt, origin)
        self._origin_log[origin].append((seq, op, flt))
        self._origin_seq[origin] = seq

    async def handle_ops(self, peer: str, obj: Dict) -> None:
        origin = obj.get("node", peer)
        epoch = obj.get("epoch", 0)
        for seq, op, flt in obj.get("ops", ()):
            self._apply(op, flt, origin, seq, epoch)

    async def handle_sync(self, peer: str, obj: Dict) -> Dict:
        """Full replacement of `origin`'s entries in OUR shard, then
        re-apply ops that raced past the snapshot cut."""
        origin = obj.get("node", peer)
        snap_seq = obj.get("seq", 0)
        self._check_epoch(origin, obj.get("epoch", 0))
        self.table.purge_node(origin)
        for flt in obj.get("filters", ()):
            self.table.add_route(flt, origin)
        for seq, op, flt in self._origin_log.get(origin, ()):
            if seq > snap_seq:
                if op == "add":
                    self.table.add_route(flt, origin)
                else:
                    self.table.delete_route(flt, origin)
        self._origin_seq[origin] = max(
            self._origin_seq.get(origin, 0), snap_seq
        )
        return {"ok": True}

    async def handle_match(self, peer: str, obj: Dict) -> Dict:
        sets = self.table.match_nodes(obj.get("topics", ()))
        return {"nodes": [sorted(s) for s in sets]}

    # ------------------------------------------------------- scatter

    async def match_scatter(
        self, topics: Sequence[str]
    ) -> List[Set[str]]:
        """One batched match per alive peer + the local owned shard;
        union per topic.  ANY scatter failure degrades the whole
        window to flooding (correct: receivers match locally)."""
        out = self.table.match_nodes(topics)
        peers = self.node.peers_alive()
        if peers:
            replies = await asyncio.gather(*(
                self.node.transport.call(
                    p, {"type": "shard_match", "topics": list(topics)},
                    timeout=2.0,
                )
                for p in peers
            ), return_exceptions=True)
            for p, rep in zip(peers, replies):
                if not isinstance(rep, dict) or "nodes" not in rep:
                    self.stats["flood"] += 1
                    self.resync_due = True
                    self.node._flush_wakeup.set()
                    alive = set(peers)
                    return [set(alive) for _ in topics]
                for i, nodes in enumerate(rep["nodes"]):
                    out[i].update(nodes)
        self.stats["scatter"] += 1
        me = self.node.name
        for s in out:
            s.discard(me)
        return out

    # -------------------------------------------------------- resync

    def on_membership_change(self) -> None:
        self.resync_due = True
        self.node._flush_wakeup.set()

    async def resync(self) -> None:
        """Re-announce every local filter to its CURRENT owner (one
        call per alive peer, empty lists included so owners purge our
        stale entries), and — after a GRACE PERIOD — purge owned
        entries whose filters are no longer ours.  The grace matters:
        each node detects a membership change on its own clock, so the
        old owner must keep answering scatter queries for a moved
        filter until every origin has had time to re-announce to the
        new owner; an immediate purge opened a silent message-loss
        window (review r5).  Stale double-answers are harmless — the
        union's receivers match locally before dispatch."""
        self.stats["resync"] += 1
        now = time.monotonic()
        for flt in list(self.table._nodes_by_filter):
            if self.owner_of(flt) != self.node.name:
                moved_at = self._moved.setdefault(flt, now)
                if now - moved_at >= self.MOVED_GRACE:
                    for origin in list(self.table.nodes_for(flt)):
                        self.table.delete_route(flt, origin)
                    self._moved.pop(flt, None)
            else:
                self._moved.pop(flt, None)
        by_owner: Dict[str, List[str]] = {}
        for flt in self.node.broker.router.topics():
            by_owner.setdefault(self.owner_of(flt), []).append(flt)
        # self-owned subset: replace directly
        me = self.node.name
        snap_seq = self._seq
        mine = set(by_owner.get(me, ()))
        for flt in list(self.table.routes_of(me)):
            if flt not in mine:
                self.table.delete_route(flt, me)
        for flt in mine:
            self.table.add_route(flt, me)
        for peer in self.node.peers_alive():
            rep = await self.node.transport.call(peer, {
                "type": "shard_sync",
                "node": me,
                "epoch": self.node._epoch,
                "seq": snap_seq,
                "filters": by_owner.get(peer, []),
            }, timeout=5.0)
            if rep is None:
                self.resync_due = True  # retry on next flush tick

    def info(self) -> Dict:
        return {
            "owned_filters": len(self.table),
            "alive": self._alive(),
            **self.stats,
        }
