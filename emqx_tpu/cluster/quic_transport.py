"""QUIC peer transport for the inter-node RPC plane.

The window-forwarding hot path moves bulk frames between nodes; over
the stock TCP links a single lost segment head-of-line-blocks every
frame behind it until the kernel retransmit timer fires.  This module
carries the SAME length-prefixed frames (transport.py's formats) over
the in-repo QUIC stack instead: loss is handled by the selective-ACK /
PTO machinery in ``quic/recovery.py`` — a 1% lossy link retransmits
exactly the missing ranges while later frames keep flowing.

Topology: ONE QUIC connection per peer pair, two client-initiated
bidirectional streams —

  * stream 0 (control): hello/hello_ack handshake, JSON casts, calls
    and their replies;
  * stream 4 (forward): binary ``forward_batch`` window frames, so a
    fat retransmitting window never stalls control traffic.

Protection is the PSK cluster profile (`quic.connection.PskKeys`):
integrity-authenticated plaintext keyed by the shared cluster secret —
the same trust model as the plaintext TCP inter-node transport, and
deliberately free of the `cryptography` dependency so the transport
runs everywhere the broker does.

The server side (`QuicPeerEndpoint`) binds UDP on the SAME port number
as the TCP listener: membership keeps one (host, port) per peer for
both transports.  The application-level handshake is the hello frame:
the dialer sends it on the control stream and waits for ``hello_ack``
— `transport_mode=auto` treats a handshake timeout as "QUIC
unavailable" and degrades that peer to the TCP PeerLink (transport.py
owns the demotion/re-probe policy).

Failpoint seams (chaos tests inject loss AT DATAGRAM GRANULARITY, so
the QUIC recovery path is what gets exercised):

  * ``cluster.quic.send`` — every outbound datagram, keyed
    ``self->peer``; drop = the network ate it;
  * ``cluster.quic.recv`` — every inbound datagram, keyed
    ``peer_addr->self``; error resets the connection like a decrypt
    failure storm would.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import failpoints
from ..aio import cancel_and_wait
from .transport import (
    NO_REPLY, PROTO_VER, _pack_bin, _pack_json, drain_frames,
)

log = logging.getLogger("emqx_tpu.cluster.quic")

CTL_STREAM = 0
FWD_STREAM = 4

# PTO probe cadence: the ack-threshold path recovers mid-flight loss
# without waiting for this; the timer only covers tail loss (the last
# datagrams of a burst with nothing behind them to trigger acks).
# Fixed rather than smoothed-RTT-based (RFC 9002) — the same honest
# loopback/LAN scope cut as quic/connection.py's fixed congestion
# window; both driver loops throttle probes on rx/probe recency so a
# link whose RTT flirts with the timer degrades to duplicates, not a
# retransmit storm.
_PTO = 0.1

# a link with data in flight that has heard NOTHING for this long is
# declared dead: the connection tears down (degraded), so auto mode's
# next send demotes to TCP and hard quic mode redials fresh — without
# this, an established link to a blackholed peer would keep buffering
# heartbeats and PTO-spraying a dead address forever, because sends
# into a UDP void "succeed"
_DEAF_AFTER = 3.0


def _make_conn(is_server: bool, psk: bytes, cid: Optional[bytes] = None):
    from ..quic.connection import QuicConnection

    return QuicConnection(is_server, psk=psk or b"\x00" * 16, cid=cid)


def _send_datagrams(conn, sendto, key: str) -> None:
    """The shared datagram-egress loop (link + endpoint sides): every
    outbound datagram passes the ``cluster.quic.send`` seam — drop and
    error both lose the datagram (QUIC recovery resends), duplicate
    sends it twice — and OSError is swallowed (datagram loss, same
    recovery)."""
    for dgram in conn.datagrams_to_send():
        if failpoints.enabled:
            try:
                act = failpoints.evaluate("cluster.quic.send", key=key)
            except failpoints.FailpointError:
                continue  # an errored send loses the datagram too
            if act == "drop":
                continue  # the network ate it; recovery resends
            if act == "duplicate":
                sendto(dgram)
        try:
            sendto(dgram)
        except OSError:
            pass  # datagram loss; QUIC recovery covers it


class QuicPeerLink:
    """One outgoing QUIC connection to a peer: the PeerLink-shaped
    API (`cast`/`cast_bin`/`call`/`close`) over a connected UDP
    socket.  ``degraded`` is True after a handshake failure — the
    auto-mode router reads it to decide TCP fallback."""

    def __init__(
        self,
        self_node: str,
        peer_node: str,
        addr: Tuple[str, int],
        psk: bytes = b"",
        connect_timeout: float = 1.0,
    ) -> None:
        self.self_node = self_node
        self.peer_node = peer_node
        self.addr = addr
        self.psk = psk
        self.connect_timeout = connect_timeout
        self.degraded = False
        self._conn = None
        self._transport = None
        self._lock = asyncio.Lock()
        self._calls: Dict[int, asyncio.Future] = {}
        self._call_seq = 0
        self._bufs: Dict[int, bytearray] = {}
        self._hello_ok = asyncio.Event()
        self._pto_task: Optional[asyncio.Task] = None
        self._last_rx = 0.0
        self._last_pto = 0.0
        self._deadline = 0.0  # handshake deadline (persists per dial)

    # ------------------------------------------------------- connect

    async def probe(self) -> None:
        """Dial + application handshake, raising on failure — the
        transport's background re-promotion probe."""
        await self._ensure()

    async def _ensure(self) -> None:
        if self._conn is not None and not self._conn.closed \
                and self._hello_ok.is_set():
            return
        if self.degraded:
            # a failed handshake marks the OBJECT dead: waiters queued
            # behind the failing dial fail fast instead of each paying
            # the full timeout (the router re-probes with a fresh link)
            raise ConnectionError(
                f"quic link to {self.peer_node} degraded"
            )
        if self._conn is not None and not self._conn.closed:
            # a cancelled earlier dial left the handshake pending:
            # fall through to the wait loop below with a fresh deadline
            conn = self._conn
        else:
            conn = None
        if conn is None:
            await self._dial()
            conn = self._conn
        loop = asyncio.get_running_loop()
        # the handshake deadline lives on the LINK, not the call: a
        # caller with a tighter bound (heartbeat wait_for) may cancel
        # out of the wait, but the clock keeps running — the next call
        # resumes the SAME handshake and fails it on time, so a
        # blackholed peer still demotes even when every individual
        # caller gives up early
        deadline = self._deadline
        try:
            while not self._hello_ok.is_set():
                if loop.time() > deadline:
                    raise ConnectionError(
                        f"quic handshake with {self.peer_node} "
                        f"({self.addr}) timed out"
                    )
                try:
                    await asyncio.wait_for(
                        self._hello_ok.wait(),
                        min(0.05, self.connect_timeout),
                    )
                except asyncio.TimeoutError:
                    conn.on_timeout()  # re-probe the hello flight
                    self._transmit()
        except ConnectionError:
            self.degraded = True
            self._teardown()
            raise
        self.degraded = False
        if self._pto_task is None:
            self._pto_task = loop.create_task(self._pto_loop())

    async def _dial(self) -> None:
        self._teardown()
        loop = asyncio.get_running_loop()
        conn = _make_conn(False, self.psk, cid=os.urandom(8))
        outer = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data: bytes, addr) -> None:
                outer._on_datagram(data)

            def error_received(self, exc) -> None:
                pass  # ICMP unreachable: the handshake timeout decides

        try:
            self._transport, _ = await loop.create_datagram_endpoint(
                lambda: _Proto(), remote_addr=self.addr
            )
        except OSError as exc:
            raise ConnectionError(
                f"quic dial to {self.addr} failed: {exc}"
            ) from exc
        self._conn = conn
        self._hello_ok.clear()
        self._deadline = loop.time() + self.connect_timeout
        # application handshake: hello on the control stream; _ensure
        # waits for the endpoint's hello_ack (loss of either flight is
        # covered by PTO-shaped probes, bounded by the timeout)
        conn.send_stream(CTL_STREAM, _pack_json({
            "type": "hello", "node": self.self_node,
            "ver": list(PROTO_VER),
        }))
        self._transmit()

    def _teardown(self) -> None:
        if self._pto_task is not None:
            self._pto_task.cancel()
            self._pto_task = None
        if self._conn is not None and not self._conn.closed:
            self._conn.close(0)
            self._transmit()
        if self._transport is not None:
            self._transport.close()
            self._transport = None
        self._conn = None
        self._bufs.clear()
        for fut in self._calls.values():
            if not fut.done():
                fut.set_exception(ConnectionError("quic link lost"))
        self._calls.clear()

    def close(self) -> None:
        self._teardown()

    # ---------------------------------------------------------- IO

    def _transmit(self) -> None:
        if self._transport is None or self._conn is None:
            return
        _send_datagrams(
            self._conn, self._transport.sendto,
            f"{self.self_node}->{self.peer_node}",
        )

    def _on_datagram(self, data: bytes) -> None:
        conn = self._conn
        if conn is None:
            return
        if failpoints.enabled:
            try:
                act = failpoints.evaluate(
                    "cluster.quic.recv",
                    key=f"{self.peer_node}->{self.self_node}",
                )
            except failpoints.FailpointError:
                conn.close(0)  # reset like a poisoned connection
                return
            if act == "drop":
                return
        self._last_rx = time.monotonic()
        conn.receive_datagram(data)
        try:
            self._drain_events(conn)
        except ConnectionError:
            log.warning("quic link %s->%s: malformed frame; resetting",
                        self.self_node, self.peer_node)
            conn.close(0)
        if conn.closed:
            # the peer reset us (endpoint restart / wedge reset):
            # pending calls fail NOW; the next send redials fresh
            for fut in self._calls.values():
                if not fut.done():
                    fut.set_exception(
                        ConnectionError("quic link reset by peer")
                    )
            self._calls.clear()
        self._transmit()

    def _drain_events(self, conn) -> None:
        for ev in conn.events():
            if ev[0] != "stream":
                continue
            _, sid, data, _fin = ev
            buf = self._bufs.setdefault(sid, bytearray())
            buf += data
            for obj in drain_frames(buf):
                self._on_frame(obj)

    def _on_frame(self, obj: Dict[str, Any]) -> None:
        mtype = obj.get("type")
        if mtype == "hello_ack":
            ver = tuple(obj.get("ver", ()))
            if ver and ver[0] == PROTO_VER[0]:
                self._hello_ok.set()
            return
        if mtype == "reply":
            fut = self._calls.pop(obj.get("call_id"), None)
            if fut is not None and not fut.done():
                fut.set_result(obj.get("result"))

    async def _pto_loop(self) -> None:
        # tick at half the PTO: ack-frequency tails flush BEFORE the
        # peer's probe timer can fire on already-delivered data
        while True:
            await asyncio.sleep(_PTO / 2)
            conn = self._conn
            if conn is None or conn.closed:
                return
            conn.ack_flush()
            self._transmit()
            now = time.monotonic()
            if not conn.has_inflight():
                continue
            if now - self._last_rx > _DEAF_AFTER:
                # data in flight, nothing heard for _DEAF_AFTER: the
                # peer is blackholed.  Sends into a UDP void look
                # successful, so WE must fail the link: degraded makes
                # the next cast fail -> auto demotes to TCP / quic
                # redials; the frame replay buffer re-delivers
                log.warning(
                    "quic link %s->%s: no acks for %.1fs with data "
                    "in flight; tearing down",
                    self.self_node, self.peer_node, _DEAF_AFTER,
                )
                self.degraded = True
                self._teardown()
                return
            # probe only when the link has gone quiet — an active ack
            # stream does threshold recovery on its own, and a probe
            # then would just spray duplicates
            if now - max(self._last_rx, self._last_pto) >= _PTO:
                self._last_pto = now
                conn.on_timeout()
                self._transmit()

    # --------------------------------------------------------- sends

    async def cast(self, obj: Dict[str, Any]) -> bool:
        # per-peer FIFO: same ordered-send contract as the TCP
        # PeerLink (route-op streams ride this)
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._conn.send_stream(CTL_STREAM, _pack_json(obj))
                self._transmit()
                return True
            except (ConnectionError, OSError):
                self._teardown()
                return False

    async def cast_bin(self, mtype: str, payload: bytes) -> bool:
        """Binary frames ride the dedicated forward stream: a lossy
        retransmitting window cannot head-of-line-block control
        frames (acks, heartbeats, route ops)."""
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._conn.send_stream(
                    FWD_STREAM, _pack_bin(mtype, payload)
                )
                self._transmit()
                return True
            except (ConnectionError, OSError):
                self._teardown()
                return False

    async def call(
        self, obj: Dict[str, Any], timeout: float = 5.0
    ) -> Optional[Dict[str, Any]]:
        # brokerlint: ignore[ASYNC103]
        async with self._lock:
            try:
                await self._ensure()
                self._call_seq += 1
                cid = self._call_seq
                obj = dict(obj, call_id=cid)
                fut: asyncio.Future = (
                    asyncio.get_running_loop().create_future()
                )
                self._calls[cid] = fut
                self._conn.send_stream(CTL_STREAM, _pack_json(obj))
                self._transmit()
            except (ConnectionError, OSError):
                self._teardown()
                return None
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, ConnectionError):
            return None


class _InboundQuic:
    """One accepted peer connection on the endpoint: stream
    reassembly, hello handling, and a serial dispatch pump (the
    per-peer FIFO the TCP serve loop provides naturally)."""

    def __init__(self, endpoint: "QuicPeerEndpoint", conn, addr) -> None:
        self.endpoint = endpoint
        self.conn = conn
        self.addr = addr
        self.peer = "?"
        self.created = time.monotonic()
        self.hello_seen = False
        self._stash: List[Tuple[int, Dict]] = []  # frames before hello
        self._bufs: Dict[int, bytearray] = {}
        self._queue: "asyncio.Queue[Tuple[int, Dict]]" = asyncio.Queue()
        self._pump = asyncio.get_running_loop().create_task(
            self._serve()
        )
        self.last_rx = time.monotonic()
        self.last_pto = 0.0

    def feed(self, data: bytes) -> None:
        self.last_rx = time.monotonic()
        self.conn.receive_datagram(data)
        try:
            for ev in self.conn.events():
                if ev[0] != "stream":
                    continue
                _, sid, payload, _fin = ev
                buf = self._bufs.setdefault(sid, bytearray())
                buf += payload
                for obj in drain_frames(buf):
                    self._on_frame(sid, obj)
        except ConnectionError:
            log.warning("quic endpoint: malformed frame from %s; "
                        "resetting", self.peer)
            self.conn.close(0)
        self.endpoint.transmit(self)

    def _on_frame(self, sid: int, obj: Dict) -> None:
        if not self.hello_seen:
            if obj.get("type") != "hello":
                # streams are independent: a forward frame can land
                # before the control stream's hello — hold it
                self._stash.append((sid, obj))
                return
            ver = tuple(obj.get("ver", ()))
            if not ver or ver[0] != PROTO_VER[0]:
                log.warning(
                    "rejecting quic peer %s: proto %s != %s",
                    obj.get("node"), ver, PROTO_VER,
                )
                self.conn.close(0)
                return
            self.peer = obj.get("node", "?")
            self.hello_seen = True
            self.conn.send_stream(sid, _pack_json({
                "type": "hello_ack", "node": self.endpoint.node,
                "ver": list(PROTO_VER),
            }))
            for pending in self._stash:
                self._queue.put_nowait(pending)
            self._stash.clear()
            return
        self._queue.put_nowait((sid, obj))

    async def _serve(self) -> None:
        while True:
            sid, obj = await self._queue.get()
            try:
                await self.endpoint.transport._dispatch_frame(
                    self.peer, obj, _QuicReplyWriter(self, sid)
                )
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                # the TCP serve loop's semantic: a handler-leaked
                # ConnectionError drops the CONNECTION — close it
                # (the CLOSE reaches the dialer, which redials and
                # replays) instead of dying silently while the conn
                # keeps acking frames nobody will ever dispatch
                log.warning(
                    "quic handler %r from %s raised ConnectionError; "
                    "resetting the connection",
                    obj.get("type"), self.peer,
                )
                self.conn.close(0)
                self.endpoint.transmit(self)
                return
            except Exception:
                log.exception(
                    "quic handler %r from %s crashed",
                    obj.get("type"), self.peer,
                )

    def close(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
            self._pump = None
        if not self.conn.closed:
            self.conn.close(0)


class _QuicReplyWriter:
    """StreamWriter-shaped adapter: call replies go back on the
    stream that carried the call."""

    __slots__ = ("inbound", "sid")

    def __init__(self, inbound: _InboundQuic, sid: int) -> None:
        self.inbound = inbound
        self.sid = sid

    def write(self, data: bytes) -> None:
        self.inbound.conn.send_stream(self.sid, data)

    async def drain(self) -> None:
        self.inbound.endpoint.transmit(self.inbound)

    def is_closing(self) -> bool:
        return self.inbound.conn.closed

    def close(self) -> None:
        pass


class QuicPeerEndpoint:
    """The node's QUIC server side: one UDP socket (same port number
    as the TCP listener), connections demuxed by the symmetric 8-byte
    connection id of the PSK profile."""

    IDLE_TIMEOUT = 60.0
    # a connection that has not completed the hello within this window
    # is RESET.  This is not just handshake hygiene: when an endpoint
    # conn dies (recv fault, endpoint restart) while the dialer's side
    # survives, the dialer keeps sending MID-STREAM offsets under the
    # same cid — the fresh endpoint conn can never reassemble from
    # offset 0, so its hello never completes.  The reset's CLOSE frame
    # reaches the dialer, whose next send redials a fresh connection
    # (offset-0 streams, new hello) and the frame-level replay buffer
    # re-delivers everything unacked.
    HELLO_DEADLINE = 2.0

    def __init__(self, transport, bind: str, port: int,
                 psk: bytes = b"") -> None:
        self.transport = transport  # the owning NodeTransport
        self.node = transport.node
        self.bind = bind
        self.port = port
        self.psk = psk
        self._udp = None
        self._by_cid: Dict[bytes, _InboundQuic] = {}
        self._pto_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        outer = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data: bytes, addr) -> None:
                outer.on_datagram(data, addr)

        self._udp, _ = await loop.create_datagram_endpoint(
            lambda: _Proto(), local_addr=(self.bind, self.port)
        )
        self._pto_task = loop.create_task(self._pto_loop())
        log.info("quic peer endpoint on %s:%d (udp)", self.bind,
                 self.port)

    async def stop(self) -> None:
        if self._pto_task is not None:
            await cancel_and_wait(self._pto_task)
            self._pto_task = None
        for inbound in list(self._by_cid.values()):
            inbound.close()
            self.transmit(inbound)
        self._by_cid.clear()
        if self._udp is not None:
            self._udp.close()
            self._udp = None

    def on_datagram(self, data: bytes, addr) -> None:
        if len(data) < 9 or data[0] & 0x80:
            return  # PSK profile peers speak short headers only
        if failpoints.enabled:
            try:
                act = failpoints.evaluate(
                    "cluster.quic.recv", key=f"{addr[0]}->{self.node}"
                )
            except failpoints.FailpointError:
                # reset whichever connection this datagram belonged to
                inbound = self._by_cid.pop(bytes(data[1:9]), None)
                if inbound is not None:
                    inbound.close()
                return
            if act == "drop":
                return
        cid = bytes(data[1:9])
        inbound = self._by_cid.get(cid)
        if inbound is None:
            conn = _make_conn(True, self.psk, cid=cid)
            inbound = self._by_cid[cid] = _InboundQuic(
                self, conn, addr
            )
        inbound.addr = addr
        inbound.feed(data)

    def transmit(self, inbound: _InboundQuic) -> None:
        if self._udp is None:
            return
        udp, addr = self._udp, inbound.addr
        _send_datagrams(
            inbound.conn,
            lambda dgram: udp.sendto(dgram, addr),
            f"{self.node}->{inbound.peer}",
        )

    async def _pto_loop(self) -> None:
        while True:
            await asyncio.sleep(_PTO / 2)
            now = time.monotonic()
            for cid, inbound in list(self._by_cid.items()):
                if inbound.conn.closed:
                    inbound.close()
                    del self._by_cid[cid]
                    continue
                if now - inbound.last_rx > self.IDLE_TIMEOUT:
                    # transmit the CLOSE (like the deadline/stop
                    # paths): an un-notified dialer would keep
                    # sending into a cid that can no longer
                    # reassemble until the wedge reset catches it
                    inbound.conn.close(0)
                    self.transmit(inbound)
                    inbound.close()
                    del self._by_cid[cid]
                    continue
                if not inbound.hello_seen and (
                    now - inbound.created > self.HELLO_DEADLINE
                ):
                    # wedged half-connection (see HELLO_DEADLINE):
                    # reset it so the dialer redials from offset 0
                    inbound.conn.close(0)
                    self.transmit(inbound)
                    inbound.close()
                    del self._by_cid[cid]
                    continue
                inbound.conn.ack_flush()
                self.transmit(inbound)
                # same quiet-link throttle as the dialer side: probe
                # only when neither rx nor a recent probe is fresher
                # than one PTO (a ~PTO-RTT link degrades to the odd
                # duplicate, not a per-tick full-backlog retransmit)
                if inbound.conn.has_inflight() and now - max(
                    inbound.last_rx, inbound.last_pto
                ) >= _PTO:
                    inbound.last_pto = now
                    inbound.conn.on_timeout()
                    self.transmit(inbound)
