"""Raft consensus for the control/durability plane.

The role `ra` plays under the reference's replicated DS and the
mnesia-logged transactional multicall under its cluster config
(/root/reference/apps/emqx_ds_builtin_raft/src/
emqx_ds_replication_layer.erl:1-1199 — Raft-replicated shard log;
/root/reference/apps/emqx_conf/src/emqx_cluster_rpc.erl:26-54 —
ordered, logged config transactions with catch-up).  Round 3 shipped
best-effort LWW buddy replication; this is the quorum upgrade: an
entry acknowledged to a caller is on a MAJORITY of nodes and survives
any single failure, including the leader's.

Classic single-group Raft (Ongaro & Ousterhout), sized to this
cluster layer:

  * roles/terms/elections with randomized timeouts; votes require the
    candidate's log to be at least as up-to-date (§5.4.1);
  * log replication with the prevLogIndex/Term consistency check and
    follower truncation on conflict;
  * commit = majority matchIndex AND entry from the current term
    (§5.4.2's commit rule);
  * persistence: term/votedFor and the log append to disk before any
    RPC answer that promises them (fsync optional — tests trade it
    for speed, production keeps it on);
  * apply callback invoked in log order exactly once per node.

RPCs ride the cluster `NodeTransport` (the gen_rpc analogue) as
``raft.<group>`` calls, so one transport carries broker forwards and
any number of Raft groups.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import failpoints
from ..aio import cancel_and_wait

log = logging.getLogger("emqx_tpu.cluster.raft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeader(Exception):
    """Raised by propose() on a non-leader; carries the leader hint."""

    def __init__(self, leader: Optional[str]) -> None:
        super().__init__(f"not leader (leader={leader})")
        self.leader = leader


class RaftNode:
    def __init__(
        self,
        node: str,
        peers: List[str],  # other members (not including self)
        transport,
        apply_cb: Callable[[int, Any], None],
        data_dir: Optional[str] = None,
        group: str = "conf",
        election_timeout: Tuple[float, float] = (0.15, 0.30),
        heartbeat: float = 0.05,
        fsync: bool = True,
    ) -> None:
        self.node = node
        self.peers = list(peers)
        self.transport = transport
        self.apply_cb = apply_cb
        self.group = group
        self.election_timeout = election_timeout
        self.heartbeat = heartbeat
        self.fsync = fsync

        self.term = 0
        self.voted_for: Optional[str] = None
        self.log: List[Tuple[int, Any]] = []  # [(term, payload)]
        self.commit_index = 0  # 1-based; 0 = nothing committed
        self.last_applied = 0
        self.role = FOLLOWER
        self.leader: Optional[str] = None

        # leader state
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._commit_waiters: Dict[int, List[asyncio.Future]] = {}

        self._timer: Optional[asyncio.TimerHandle] = None
        self._hb_task: Optional[asyncio.Task] = None
        # fire-and-forget work (elections, replication nudges): the
        # set keeps a strong reference until each task ends, so none
        # is garbage-collected mid-flight with its exception dropped
        self._bg: set = set()
        self._stopped = False
        self._meta_lock = threading.Lock()
        # when we last heard a (valid-term) AppendEntries: prevote
        # denial window — a live leader means no election is needed
        self._last_leader_contact = 0.0

        self._dir = data_dir
        self._log_f = None
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            self._meta_path = os.path.join(
                data_dir, f"raft-{group}-meta.json"
            )
            self._log_path = os.path.join(
                data_dir, f"raft-{group}-log.jsonl"
            )
            self._recover()

        # concurrent: the propose kind awaits a commit whose append
        # replies may share the connection; votes/appends are
        # order-insensitive (term/index guarded)
        transport.on(f"raft.{group}", self._on_rpc, concurrent=True)

    # ---------------------------------------------------- persistence

    def _recover(self) -> None:
        try:
            with open(self._meta_path) as f:
                meta = json.load(f)
            self.term = int(meta.get("term", 0))
            self.voted_for = meta.get("voted_for")
        except (OSError, json.JSONDecodeError):
            pass
        try:
            if not os.path.exists(self._log_path):
                return
            with open(self._log_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    if rec[0] == "a":  # append: ["a", index, term, payload]
                        idx = int(rec[1])
                        del self.log[idx - 1:]  # truncate any conflict
                        self.log.append((int(rec[2]), rec[3]))
                    elif rec[0] == "t":  # truncate-from: ["t", index]
                        del self.log[int(rec[1]) - 1:]
        except (OSError, json.JSONDecodeError, IndexError, ValueError):
            log.exception("raft[%s] log recovery stopped early",
                          self.group)

    def _persist_meta(self) -> None:
        """Write term/votedFor (no fsync — term bumps alone are safe
        to lose: a vote is only GRANTED through the durable path
        below)."""
        if self._dir is None:
            return
        with self._meta_lock:
            tmp = self._meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"term": self.term, "voted_for": self.voted_for}, f
                )
            os.replace(tmp, self._meta_path)

    def _persist_meta_fsync_blocking(self) -> None:
        if self._dir is None:
            return
        # serialized: rapid term churn (a healing partition's dueling
        # elections) queues several executor jobs; two sharing the one
        # .tmp path race write-vs-replace and crash with ENOENT
        with self._meta_lock:
            tmp = self._meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"term": self.term, "voted_for": self.voted_for}, f
                )
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self._meta_path)

    async def _persist_meta_durable(self) -> None:
        """votedFor must hit disk BEFORE a vote is granted or a
        candidacy starts (§5.2: a crashed-and-restarted node must not
        vote twice in one term); the fsync runs in an executor so the
        event loop serving MQTT traffic never stalls on it."""
        await asyncio.get_running_loop().run_in_executor(
            None, self._persist_meta_fsync_blocking
        )

    def _log_file(self):
        if self._log_f is None:
            self._log_f = open(self._log_path, "a")
        return self._log_f

    def _persist_append(self, start_index: int,
                        entries: List[Tuple[int, Any]]) -> None:
        """Write+flush synchronously (ordering); the durability fsync
        is awaited separately by the async paths that must not answer
        before it (`_fsync_log`), keeping multi-ms fsyncs off the
        event loop."""
        if self._dir is None:
            return
        f = self._log_file()
        for k, (t, payload) in enumerate(entries):
            f.write(json.dumps(
                ["a", start_index + k, t, payload],
                separators=(",", ":"),
            ) + "\n")
        f.flush()

    async def _fsync_log(self) -> None:
        if self._dir is None or not self.fsync or self._log_f is None:
            return
        fd = self._log_f.fileno()
        await asyncio.get_running_loop().run_in_executor(
            None, os.fsync, fd
        )

    def _persist_truncate(self, from_index: int) -> None:
        if self._dir is None:
            return
        f = self._log_file()
        f.write(json.dumps(["t", from_index]) + "\n")
        f.flush()
        if self.fsync:
            os.fsync(f.fileno())

    # ------------------------------------------------------ lifecycle

    def add_member(self, node: str) -> bool:
        """Pre-bootstrap membership adoption: a peer learned via
        gossip may join the quorum ONLY while nothing has been
        committed or logged here — chained bring-up (n1 alone, n2
        seeding n1, ...) otherwise leaves asymmetric membership views
        and a silently-broken quorum.  Once entries exist, membership
        is frozen (joint consensus is out of scope, as in start())."""
        if node == self.node or node in self.peers:
            return False
        if self.log or self.commit_index > 0:
            log.warning(
                "raft[%s] %s: refusing post-bootstrap member %s "
                "(membership frozen; restart with full seeds)",
                self.group, self.node, node,
            )
            return False
        self.peers.append(node)
        self.next_index.setdefault(node, 1)
        self.match_index.setdefault(node, 0)
        log.info("raft[%s] %s: adopted member %s (pre-bootstrap)",
                 self.group, self.node, node)
        return True

    def start(self) -> None:
        self._stopped = False
        self._become_follower(self.term, None)

    async def stop(self) -> None:
        self._stopped = True
        self._cancel_timer()
        if self._hb_task is not None:
            await cancel_and_wait(self._hb_task)
            self._hb_task = None
        for task in list(self._bg):  # in-flight elections/nudges
            await cancel_and_wait(task)
        self._bg.clear()
        for waiters in self._commit_waiters.values():
            for fut in waiters:
                if not fut.done():
                    fut.set_exception(NotLeader(None))
        self._commit_waiters.clear()
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None

    # --------------------------------------------------------- timers

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _reset_election_timer(self) -> None:
        self._cancel_timer()
        if self._stopped:
            return
        delay = random.uniform(*self.election_timeout)
        self._timer = asyncio.get_running_loop().call_later(
            delay, self._election_timeout_fired
        )

    def _spawn(self, coro) -> asyncio.Task:
        """Retained fire-and-forget task (ASYNC105: a bare
        ``create_task`` is GC-bait and swallows crashes)."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)
        return task

    def _election_timeout_fired(self) -> None:
        if self._stopped or self.role == LEADER:
            return
        self._spawn(self._run_election())

    # ------------------------------------------------------ elections

    def _last(self) -> Tuple[int, int]:
        """(lastLogIndex, lastLogTerm), 1-based."""
        if not self.log:
            return 0, 0
        return len(self.log), self.log[-1][0]

    async def _run_election(self) -> None:
        # PreVote (§9.6, the raft dissertation): before bumping the
        # term, ask whether a majority WOULD vote for us.  A node cut
        # off by a partition otherwise inflates its term unboundedly
        # and, at heal time, forces the healthy majority through
        # step-downs and dueling re-elections for seconds; with
        # prevote it rejoins as a follower at the cluster's term and
        # converges on the next heartbeat.
        if self.peers and not await self._prevote():
            self._reset_election_timer()
            return
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.node
        self.leader = None
        await self._persist_meta_durable()
        term = self.term
        self._reset_election_timer()
        last_idx, last_term = self._last()
        votes = 1  # self

        async def ask(peer: str):
            return peer, await self.transport.call(peer, {
                "type": f"raft.{self.group}",
                "kind": "vote",
                "term": term,
                "candidate": self.node,
                "last_log_index": last_idx,
                "last_log_term": last_term,
            }, timeout=self.election_timeout[0])

        for coro in asyncio.as_completed([ask(p) for p in self.peers]):
            peer, resp = await coro
            if self.term != term or self.role != CANDIDATE:
                return  # a higher term arrived meanwhile
            if resp is None:
                continue
            if resp.get("term", 0) > self.term:
                self._become_follower(resp["term"], None)
                return
            if resp.get("granted"):
                votes += 1
                if votes * 2 > len(self.peers) + 1:
                    self._become_leader()
                    return

    async def _prevote(self) -> bool:
        term = self.term
        last_idx, last_term = self._last()

        async def ask(peer: str):
            return await self.transport.call(peer, {
                "type": f"raft.{self.group}",
                "kind": "prevote",
                "term": term + 1,
                "candidate": self.node,
                "last_log_index": last_idx,
                "last_log_term": last_term,
            }, timeout=self.election_timeout[0])

        granted = 1  # self
        for coro in asyncio.as_completed([ask(p) for p in self.peers]):
            resp = await coro
            if self.term != term or self.role == LEADER:
                return False
            if resp is not None and resp.get("granted"):
                granted += 1
                if granted * 2 > len(self.peers) + 1:
                    return True
        return granted * 2 > len(self.peers) + 1

    def _become_follower(self, term: int, leader: Optional[str]) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta()
        was_leader = self.role == LEADER
        self.role = FOLLOWER
        self.leader = leader
        if was_leader and self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
            # proposals in flight can no longer be confirmed by us
            for waiters in self._commit_waiters.values():
                for fut in waiters:
                    if not fut.done():
                        fut.set_exception(NotLeader(leader))
            self._commit_waiters.clear()
        self._reset_election_timer()

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader = self.node
        self._cancel_timer()
        last_idx, _ = self._last()
        self.next_index = {p: last_idx + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        log.info("raft[%s] %s is leader for term %d",
                 self.group, self.node, self.term)
        self._hb_task = asyncio.get_running_loop().create_task(
            self._lead()
        )

    # ----------------------------------------------------- leadership

    async def _lead(self) -> None:
        try:
            while self.role == LEADER and not self._stopped:
                await asyncio.gather(
                    *(self._replicate(p) for p in self.peers),
                    return_exceptions=True,
                )
                await asyncio.sleep(self.heartbeat)
        except asyncio.CancelledError:
            raise

    async def _replicate(self, peer: str) -> None:
        if self.role != LEADER:
            return
        term = self.term
        ni = self.next_index.get(peer, 1)
        prev_idx = ni - 1
        prev_term = self.log[prev_idx - 1][0] if prev_idx >= 1 else 0
        entries = self.log[ni - 1: ni - 1 + 256]
        resp = await self.transport.call(peer, {
            "type": f"raft.{self.group}",
            "kind": "append",
            "term": term,
            "leader": self.node,
            "prev_log_index": prev_idx,
            "prev_log_term": prev_term,
            "entries": [[t, p] for t, p in entries],
            "leader_commit": self.commit_index,
        }, timeout=max(self.heartbeat * 4, 0.2))
        if resp is None or self.role != LEADER or self.term != term:
            return
        if resp.get("term", 0) > self.term:
            self._become_follower(resp["term"], None)
            return
        if resp.get("ok"):
            if entries:
                self.match_index[peer] = prev_idx + len(entries)
                self.next_index[peer] = self.match_index[peer] + 1
                self._advance_commit()
        else:
            # consistency check failed: back off (the follower hints
            # how far back its log actually reaches)
            hint = resp.get("last_index")
            self.next_index[peer] = (
                min(ni - 1, int(hint) + 1) if hint is not None
                else max(ni - 1, 1)
            )

    def _advance_commit(self) -> None:
        """Majority matchIndex AND current-term entry (§5.4.2)."""
        last_idx, _ = self._last()
        for idx in range(last_idx, self.commit_index, -1):
            if self.log[idx - 1][0] != self.term:
                break  # only current-term entries commit by counting
            votes = 1 + sum(
                1 for p in self.peers if self.match_index.get(p, 0) >= idx
            )
            if votes * 2 > len(self.peers) + 1:
                self._set_commit(idx)
                break

    def _set_commit(self, idx: int) -> None:
        if idx <= self.commit_index:
            return
        self.commit_index = idx
        self._apply_ready()
        for i in [k for k in self._commit_waiters if k <= idx]:
            for fut in self._commit_waiters.pop(i):
                if not fut.done():
                    fut.set_result(i)

    def _apply_ready(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            try:
                self.apply_cb(self.last_applied,
                              self.log[self.last_applied - 1][1])
            except Exception:
                log.exception("raft[%s] apply of entry %d failed",
                              self.group, self.last_applied)

    async def propose(self, payload: Any, timeout: float = 5.0) -> int:
        """Append an entry; resolves with its index once COMMITTED on
        a majority (the quorum ack).  Raises NotLeader elsewhere —
        callers redirect to `.leader`."""
        if self.role != LEADER:
            raise NotLeader(self.leader)
        self.log.append((self.term, payload))
        idx = len(self.log)
        self._persist_append(idx, [(self.term, payload)])
        # register the waiter BEFORE the fsync await: a leadership
        # loss during the executor hop fails waiters via
        # _become_follower — ours must already be on the list or it
        # would strand for the full timeout
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._commit_waiters.setdefault(idx, []).append(fut)
        try:
            await self._fsync_log()  # durable BEFORE any ack can form
        except Exception:
            waiters = self._commit_waiters.get(idx)
            if waiters and fut in waiters:
                waiters.remove(fut)
            raise
        if not self.peers:  # single-node group commits immediately
            self._set_commit(idx)
        else:
            # nudge replication now instead of waiting a heartbeat
            self._spawn(self._replicate_all_once())
        return await asyncio.wait_for(fut, timeout)

    async def _replicate_all_once(self) -> None:
        await asyncio.gather(
            *(self._replicate(p) for p in self.peers),
            return_exceptions=True,
        )

    # ------------------------------------------------------------ RPC

    async def _on_rpc(self, peer: str, obj: Dict) -> Optional[Dict]:
        kind = obj.get("kind")
        if failpoints.enabled:
            # RPC-loss seam: drop suppresses the reply frame entirely
            # (NO_REPLY sentinel — the caller burns its full RPC
            # timeout, exactly like a lost reply); delay injects
            # consensus latency; error resets the handler like a peer
            # crash
            act = await failpoints.evaluate_async(
                "cluster.raft.rpc", key=f"{self.group}:{kind}@{self.node}"
            )
            if act == "drop":
                from .transport import NO_REPLY

                return NO_REPLY
        if kind == "vote":
            return await self._on_vote(obj)
        if kind == "prevote":
            return self._on_prevote(obj)
        if kind == "append":
            return await self._on_append(obj)
        if kind == "propose":
            # follower-forwarded proposal (the emqx_cluster_rpc
            # "initiate on the core" shape)
            if self.role != LEADER:
                return {"ok": False, "leader": self.leader}
            try:
                idx = await self.propose(obj.get("payload"))
                return {"ok": True, "index": idx}
            except (NotLeader, asyncio.TimeoutError):
                return {"ok": False, "leader": self.leader}
        return None

    def _on_prevote(self, obj: Dict) -> Dict:
        """Non-binding poll: grants do NOT bump terms, persist
        anything, or reset timers.  Denied while we hear from a live
        leader (heartbeat within the minimum election timeout) so a
        rejoining partitioned node cannot disrupt a healthy quorum."""
        granted = False
        if int(obj["term"]) >= self.term and (
            time.monotonic() - self._last_leader_contact
            >= self.election_timeout[0]
        ):
            my_idx, my_term = self._last()
            c_idx = int(obj["last_log_index"])
            c_term = int(obj["last_log_term"])
            granted = (c_term, c_idx) >= (my_term, my_idx)
        return {"term": self.term, "granted": granted}

    async def _on_vote(self, obj: Dict) -> Dict:
        term = int(obj["term"])
        if term > self.term:
            self._become_follower(term, None)
        granted = False
        if term == self.term and self.voted_for in (
            None, obj["candidate"]
        ):
            # §5.4.1: candidate's log must be at least as up-to-date
            my_idx, my_term = self._last()
            c_idx = int(obj["last_log_index"])
            c_term = int(obj["last_log_term"])
            if (c_term, c_idx) >= (my_term, my_idx):
                granted = True
                self.voted_for = obj["candidate"]
                await self._persist_meta_durable()
                self._reset_election_timer()
        return {"term": self.term, "granted": granted}

    async def _on_append(self, obj: Dict) -> Dict:
        term = int(obj["term"])
        if term < self.term:
            return {"term": self.term, "ok": False}
        if term > self.term or self.role != FOLLOWER:
            self._become_follower(term, obj.get("leader"))
        else:
            self.leader = obj.get("leader")
            self._reset_election_timer()
        self._last_leader_contact = time.monotonic()
        prev_idx = int(obj["prev_log_index"])
        prev_term = int(obj["prev_log_term"])
        last_idx, _ = self._last()
        if prev_idx > last_idx or (
            prev_idx >= 1 and self.log[prev_idx - 1][0] != prev_term
        ):
            return {
                "term": self.term, "ok": False,
                "last_index": min(last_idx, prev_idx - 1),
            }
        entries = [(int(t), p) for t, p in obj.get("entries", [])]
        if entries:
            # drop conflicting suffix, append the rest
            write_from = None
            for k, (t, _p) in enumerate(entries):
                idx = prev_idx + 1 + k
                if idx > last_idx:
                    write_from = k
                    break
                if self.log[idx - 1][0] != t:
                    del self.log[idx - 1:]
                    self._persist_truncate(idx)
                    write_from = k
                    break
            if write_from is not None:
                new = entries[write_from:]
                start = prev_idx + 1 + write_from
                self.log.extend(new)
                self._persist_append(start, new)
                # durable BEFORE answering ok: the leader counts this
                # node toward the commit majority on our reply
                await self._fsync_log()
        leader_commit = int(obj.get("leader_commit", 0))
        if leader_commit > self.commit_index:
            # clamp to the index of the LAST ENTRY THIS RPC verified
            # (§5.3 figure 2), not our log length: a divergent stale
            # suffix beyond the verified range must never commit here
            verified = prev_idx + len(entries)
            self.commit_index = max(
                self.commit_index, min(leader_commit, verified)
            )
            self._apply_ready()
        return {"term": self.term, "ok": True}

    # --------------------------------------------------------- client

    async def submit(self, payload: Any, timeout: float = 5.0) -> int:
        """Propose from anywhere: leaders commit directly, followers
        forward to the known leader (one hop, as emqx_cluster_rpc
        initiates transactions on a core node)."""
        deadline = time.monotonic() + timeout
        while True:
            if self.role == LEADER:
                try:
                    return await self.propose(
                        payload, timeout=deadline - time.monotonic()
                    )
                except NotLeader:
                    pass
            target = self.leader
            if target is not None and target != self.node:
                resp = await self.transport.call(target, {
                    "type": f"raft.{self.group}",
                    "kind": "propose",
                    "payload": payload,
                }, timeout=min(2.0, max(deadline - time.monotonic(),
                                        0.1)))
                if resp and resp.get("ok"):
                    return int(resp["index"])
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"raft[{self.group}] submit timed out (leader="
                    f"{self.leader})"
                )
            await asyncio.sleep(0.05)

    def info(self) -> Dict:
        return {
            "group": self.group,
            "role": self.role,
            "term": self.term,
            "leader": self.leader,
            "log_len": len(self.log),
            "commit_index": self.commit_index,
        }
