"""Cluster route table: full replica of (filter -> nodes) per node.

The reference replicates `?ROUTE_TAB`/`?ROUTE_TAB_FILTERS` to every
node via mria so route lookup is always node-local
(/root/reference/apps/emqx/src/emqx_router.erl:133-162); cross-node
consistency comes from broadcasting route ops.  Same shape here: each
node applies every peer's route deltas to its replica, and the replica
indexes wildcard filters in its own MatchEngine so the remote-routing
lookup is the same batched device step as local routing.

fid convention: the filter string itself (one engine entry per filter,
whatever number of nodes subscribe to it).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..engine import MatchEngine


class ClusterRouteTable:
    def __init__(self, engine: Optional[MatchEngine] = None) -> None:
        # not `engine or ...`: an empty MatchEngine is falsy (__len__)
        self.engine = engine if engine is not None else MatchEngine()
        # filter -> set of node names holding local subscribers for it
        self._nodes_by_filter: Dict[str, Set[str]] = {}
        self._filters_by_node: Dict[str, Set[str]] = {}

    def add_route(self, flt: str, node: str) -> bool:
        """Returns True when (flt, node) was not already present."""
        nodes = self._nodes_by_filter.get(flt)
        if nodes is None:
            nodes = self._nodes_by_filter[flt] = set()
            self.engine.insert(flt, flt)
        new = node not in nodes
        nodes.add(node)
        self._filters_by_node.setdefault(node, set()).add(flt)
        return new

    def delete_route(self, flt: str, node: str) -> None:
        nodes = self._nodes_by_filter.get(flt)
        if nodes is None:
            return
        nodes.discard(node)
        if not nodes:
            del self._nodes_by_filter[flt]
            self.engine.delete(flt)
        flts = self._filters_by_node.get(node)
        if flts is not None:
            flts.discard(flt)
            if not flts:
                del self._filters_by_node[node]

    def purge_node(self, node: str) -> int:
        """Drop every route of a dead node (emqx_router_helper's
        cleanup_routes, emqx_router.erl:316-323)."""
        flts = list(self._filters_by_node.get(node, ()))
        for flt in flts:
            self.delete_route(flt, node)
        return len(flts)

    def routes_of(self, node: str) -> Set[str]:
        return set(self._filters_by_node.get(node, ()))

    def nodes_for(self, flt: str) -> Set[str]:
        return set(self._nodes_by_filter.get(flt, ()))

    def match_nodes(
        self, topics: Sequence[str], exclude: Optional[str] = None
    ) -> List[Set[str]]:
        """Per topic, the set of nodes with at least one matching route
        (the aggregation emqx_broker:aggre does over match_routes,
        emqx_broker.erl:339-377)."""
        matched = self.engine.match_batch(topics)
        out: List[Set[str]] = []
        for filters in matched:
            nodes: Set[str] = set()
            for flt in filters:
                nodes |= self._nodes_by_filter.get(flt, ())
            if exclude is not None:
                nodes.discard(exclude)
            out.append(nodes)
        return out

    def all_routes(self) -> List[Dict[str, object]]:
        return [
            {"topic": flt, "nodes": sorted(nodes)}
            for flt, nodes in self._nodes_by_filter.items()
        ]

    def __len__(self) -> int:
        return len(self._nodes_by_filter)
