"""Retained-message store + replay on subscribe.

Re-creates `emqx_retainer` (/root/reference/apps/emqx_retainer/src/
emqx_retainer.erl:98-110 backend contract; emqx_retainer_index.erl own
topic index; rate-limited dispatcher :312): retained messages keyed by
topic, with *reverse* matching on subscribe — a new filter is matched
against stored topic names.  The store reuses `HostTrie` as its index
by inserting each retained topic as a (wildcard-free) filter, so
`match_words` with a concrete-name walk is replaced by a dedicated
reverse walk below.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Tuple

from . import topic as T
from .message import Message


class _Node:
    __slots__ = ("children", "msg")

    def __init__(self) -> None:
        self.children: Dict[str, _Node] = {}
        self.msg: Optional[Message] = None


class Retainer:
    def __init__(
        self,
        max_retained_messages: int = 0,
        max_payload_size: int = 1024 * 1024,
        msg_expiry_interval: float = 0.0,
        enable: bool = True,
    ) -> None:
        self.enable = enable
        self.max_retained_messages = max_retained_messages
        self.max_payload_size = max_payload_size
        self.msg_expiry_interval = msg_expiry_interval
        self._root = _Node()
        self._count = 0

    def __len__(self) -> int:
        return self._count

    # -------------------------------------------------------- store

    def store(self, msg: Message) -> bool:
        """Apply a retain-flagged publish: empty payload deletes
        ([MQTT-3.3.1-6]); otherwise insert/replace.  Returns False when
        refused (limits)."""
        if not self.enable:
            return False
        if not msg.payload:
            self.delete(msg.topic)
            return True
        if len(msg.payload) > self.max_payload_size:
            return False
        ws = T.words(msg.topic)
        node = self._root
        path = []
        for w in ws:
            path.append(node)
            node = node.children.setdefault(w, _Node())
        if node.msg is None:
            if (
                self.max_retained_messages
                and self._count >= self.max_retained_messages
            ):
                # roll back any freshly created empty path
                self._prune(ws)
                return False
            self._count += 1
        node.msg = msg
        return True

    def delete(self, topic: str) -> bool:
        ws = T.words(topic)
        node = self._root
        for w in ws:
            node = node.children.get(w)
            if node is None:
                return False
        if node.msg is None:
            return False
        node.msg = None
        self._count -= 1
        self._prune(ws)
        return True

    def _prune(self, ws: Tuple[str, ...]) -> None:
        path: List[Tuple[_Node, str]] = []
        node = self._root
        for w in ws:
            nxt = node.children.get(w)
            if nxt is None:
                return
            path.append((node, w))
            node = nxt
        for parent, w in reversed(path):
            child = parent.children[w]
            if child.children or child.msg is not None:
                break
            del parent.children[w]

    # -------------------------------------------------------- match

    def match(self, flt: str, now: Optional[float] = None) -> List[Message]:
        """All live retained messages whose topic matches filter `flt`
        (reverse matching: filter vs stored names)."""
        fw = T.words(T.real_topic(flt))
        now = now if now is not None else time.time()
        out: List[Message] = []
        self._walk(self._root, fw, 0, False, out)
        return [m for m in out if not self._expired(m, now)]

    def _expired(self, msg: Message, now: float) -> bool:
        if msg.expired(now):
            self._maybe_gc(msg)
            return True
        if self.msg_expiry_interval and (
            now > msg.timestamp + self.msg_expiry_interval
        ):
            self._maybe_gc(msg)
            return True
        return False

    def _maybe_gc(self, msg: Message) -> None:
        self.delete(msg.topic)

    def _walk(
        self,
        node: _Node,
        fw: Tuple[str, ...],
        i: int,
        past_root: bool,
        out: List[Message],
    ) -> None:
        if i == len(fw):
            if node.msg is not None:
                out.append(node.msg)
            return
        w = fw[i]
        if w == T.HASH:
            # '#' matches the parent level too; '$'-topics are excluded
            # from root wildcards (emqx_topic.erl:81-84)
            self._collect(node, out, exclude_dollar=not past_root)
            return
        if w == T.PLUS:
            for name, child in node.children.items():
                if not past_root and name.startswith("$"):
                    continue
                self._walk(child, fw, i + 1, True, out)
            return
        child = node.children.get(w)
        if child is not None:
            self._walk(child, fw, i + 1, True, out)

    def _collect(
        self, node: _Node, out: List[Message], exclude_dollar: bool
    ) -> None:
        if node.msg is not None:
            out.append(node.msg)
        for name, child in node.children.items():
            if exclude_dollar and name.startswith("$"):
                continue
            self._collect(child, out, exclude_dollar=False)

    def topics(self) -> List[str]:
        out: List[str] = []

        def rec(node: _Node, path: List[str]) -> None:
            if node.msg is not None:
                out.append("/".join(path))
            for name, child in node.children.items():
                rec(child, path + [name])

        rec(self._root, [])
        return out

    def clear(self) -> None:
        self._root = _Node()
        self._count = 0
