"""Hot-path window profiler: stage-latency histograms, a flight
recorder of recent dispatch windows, and Chrome trace-event export.

The reference ships its observability as first-class subsystems —
`emqx_prometheus` exposition, `emqx_opentelemetry` OTLP metrics/spans,
`emqx_slow_subs` — but its hot path is per-message, so per-hook
counters suffice.  This broker's hot path is *batched* (window
assembly → trie-automaton match → CSR expand → encode-once → corked
flush), and a flat counter cannot say **which stage** of the window
pipeline a stall lives in.  Three pieces close that gap:

``Histogram``
    Fixed log2-bucket latency histogram: precomputed bounds, O(1)
    ``int.bit_length`` bucket index, mergeable snapshots.  Recording
    is lock-amortized the way ``Metrics.inc_bulk`` is — the profiler
    takes ONE lock per committed window for all of the window's stage
    samples, not one per sample.

``Profiler`` / ``WindowRecord``
    Per-window stage spans (batch-wait, prepare, match submit/wait
    with host-vs-device path + breaker state, CSR expand, deliver,
    cork flush, end-to-end publish→delivery) collected by the broker
    with two ``perf_counter`` calls per stage, plus engine lifecycle
    events (XLA shape compiles, ``device_put`` transfer bytes, delta
    folds) recorded from the builder threads.

Flight recorder
    A fixed ring of the last N ``WindowRecord``s, always on and
    near-free, dumpable over REST (``/api/v5/profiler``) and as
    Chrome trace-event JSON (``/api/v5/profiler/trace``) that loads
    directly in Perfetto — a stall is diagnosable post-hoc without a
    reproducer.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

# log2 bucket upper bounds (inclusive), shared by every Histogram:
# bucket i holds integer values v with bit_length(v) == i, i.e.
# v <= 2**i - 1; the last bucket is +Inf.  31 finite bounds cover one
# microsecond to ~35 minutes when values are recorded in µs.
N_BUCKETS = 32
BOUNDS: Tuple[int, ...] = tuple((1 << i) - 1 for i in range(N_BUCKETS - 1))


class HistogramSnapshot:
    """Immutable point-in-time copy of a Histogram; snapshots merge
    (per-bucket add) so per-shard / per-process histograms aggregate
    without losing percentile fidelity."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, counts: Sequence[int], total: float, count: int):
        self.counts = tuple(counts)
        self.sum = total
        self.count = count

    def merge(self, other: "HistogramSnapshot") -> "HistogramSnapshot":
        return HistogramSnapshot(
            tuple(a + b for a, b in zip(self.counts, other.counts)),
            self.sum + other.sum,
            self.count + other.count,
        )

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 100]): linear interpolation
        inside the containing bucket.  0.0 with no samples."""
        if self.count == 0:
            return 0.0
        target = self.count * min(max(q, 0.0), 100.0) / 100.0
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = 0 if i == 0 else BOUNDS[i - 1] + 1
                hi = (
                    BOUNDS[i]
                    if i < len(BOUNDS)
                    # open-ended last bucket: cap at the mean of what
                    # landed there (sum bounds it) or 2x the last edge
                    else max(BOUNDS[-1] * 2, lo)
                )
                frac = (target - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return float(BOUNDS[-1])

    def raw_dict(self) -> Dict[str, object]:
        """Lossless wire form (counts included) — the match service
        ships these over the control socket so the broker side can
        re-expose REAL histograms (prometheus buckets, mergeable
        snapshots), not just point percentiles."""
        return {
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "HistogramSnapshot":
        counts = list(d.get("counts") or [])
        counts = (counts + [0] * N_BUCKETS)[:N_BUCKETS]
        return cls(counts, float(d.get("sum", 0.0)),
                   int(d.get("count", 0)))

    def to_dict(self) -> Dict[str, object]:
        return {
            "count": self.count,
            "sum": round(self.sum, 3),
            "p50": round(self.percentile(50), 3),
            "p95": round(self.percentile(95), 3),
            "p99": round(self.percentile(99), 3),
            "max_bucket_le": (
                BOUNDS[min(
                    max(i for i, c in enumerate(self.counts) if c),
                    len(BOUNDS) - 1,
                )]
                if self.count else 0
            ),
        }


class Histogram:
    """Fixed log2-bucket histogram.  ``record`` is O(1): the bucket
    index is ``int(value).bit_length()`` against precomputed bounds —
    no search, no allocation.  Thread-safe via its own lock unless the
    owner passes a shared one (the Profiler amortizes ONE lock across
    every histogram it owns, one acquisition per window)."""

    __slots__ = ("_counts", "_sum", "_count", "_lock")

    def __init__(self, lock: Optional[threading.Lock] = None) -> None:
        self._counts = [0] * N_BUCKETS
        self._sum = 0.0
        self._count = 0
        self._lock = lock if lock is not None else threading.Lock()

    @staticmethod
    def bucket_index(value: float) -> int:
        v = int(value)
        if v <= 0:
            return 0
        i = v.bit_length()
        return i if i < N_BUCKETS else N_BUCKETS - 1

    def _record_locked(self, value: float) -> None:
        """Caller holds the lock (bulk paths)."""
        self._counts[Histogram.bucket_index(value)] += 1
        self._sum += value
        self._count += 1

    def record(self, value: float) -> None:
        with self._lock:
            self._record_locked(value)

    def record_many(self, values: Sequence[float]) -> None:
        """Bulk record under ONE lock acquisition — per-window use."""
        if not values:
            return
        with self._lock:
            for v in values:
                self._record_locked(v)

    def snapshot(self) -> HistogramSnapshot:
        with self._lock:
            return HistogramSnapshot(
                list(self._counts), self._sum, self._count
            )

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * N_BUCKETS
            self._sum = 0.0
            self._count = 0


class WindowRecord:
    """One dispatch window's flight-record entry: stage spans plus
    sizes, the match path taken and the breaker state.  Mutated by
    exactly one window's happens-before chain (collector → executor →
    dispatch loop), so it needs no lock of its own."""

    __slots__ = (
        "seq", "wall0", "t0", "_t_last", "n_msgs", "n_deliveries",
        "n_clients", "path", "breaker_open", "source", "spans",
        "subs", "e2e_ms",
    )

    def __init__(self, seq: int, n_msgs: int, source: str) -> None:
        now = time.perf_counter()
        self.seq = seq
        self.wall0 = time.time()
        self.t0 = now
        self._t_last = now
        self.n_msgs = n_msgs
        self.n_deliveries = 0
        self.n_clients = 0
        self.path = ""  # "host" | "dev" | "host-fallback"
        self.breaker_open = False
        self.source = source  # "publish" | "batcher" | "forwarded"
        self.spans: List[Tuple[str, float, float]] = []  # (name, off, dur)
        # nested sub-stages: (name, dur) accumulated inside a parent
        # span (e.g. the native ``assemble`` share of ``deliver``) —
        # histogrammed like spans but kept out of the trace's B/E
        # track, whose spans must stay contiguous
        self.subs: List[Tuple[str, float]] = []
        self.e2e_ms: List[float] = []

    def lap(self, name: str) -> None:
        """Close the span running since the previous lap (or since
        construction) under ``name`` — two perf_counter reads per
        stage, nothing else on the hot path."""
        now = time.perf_counter()
        self.spans.append((name, self._t_last - self.t0, now - self._t_last))
        self._t_last = now

    def sub(self, name: str, dur_s: float) -> None:
        """Record a nested sub-stage total (caller-accumulated)."""
        self.subs.append((name, dur_s))

    def to_dict(self) -> Dict[str, object]:
        return {
            "seq": self.seq,
            "at": self.wall0,
            "source": self.source,
            "n_msgs": self.n_msgs,
            "n_deliveries": self.n_deliveries,
            "n_clients": self.n_clients,
            "path": self.path,
            "breaker_open": self.breaker_open,
            "stages_us": {
                **{
                    name: round(dur * 1e6, 1)
                    for name, _off, dur in self.spans
                },
                **{
                    name: round(dur * 1e6, 1)
                    for name, dur in self.subs
                },
            },
            "e2e_ms": [round(v, 3) for v in self.e2e_ms[:8]],
        }


class Profiler:
    """The broker's window profiler: named histograms (one shared
    lock, bulk-recorded per window), the flight-recorder ring, and an
    engine-event ring.  ``enabled=False`` turns the whole thing into
    a no-op (``begin`` returns None and every call site guards)."""

    # stage histograms pre-created so exposition order is stable
    STAGES = (
        "batch_wait", "prepare", "match_submit", "match_wait",
        "dispatch_wait", "replay_read", "expand", "decide", "deliver",
        "assemble", "flush", "rules", "tokenize", "ds_sync", "e2e",
    )

    def __init__(
        self,
        ring_size: int = 256,
        events_cap: int = 256,
        enabled: bool = True,
        process_label: str = "emqx_tpu",
        pid: Optional[int] = None,
    ) -> None:
        self.enabled = enabled
        # explicit process identity for the trace export: without a
        # real pid + node label every node/worker's tracks land under
        # one implicit process and merged multi-node timelines
        # interleave into a single row group
        self.process_label = process_label
        self.pid = pid if pid is not None else os.getpid()
        self._hlock = threading.Lock()  # ONE lock for all histograms
        self._hist: Dict[str, Histogram] = {
            name: Histogram(lock=self._hlock) for name in self.STAGES
        }
        self._ring: List[Optional[WindowRecord]] = [None] * max(ring_size, 1)
        self._ring_lock = threading.Lock()
        self._seq = 0
        # engine lifecycle events: (kind, wall_ts, dur_s, meta)
        self._events: deque = deque(maxlen=max(events_cap, 1))
        # optional flightrec.FlightRecorder: every committed window is
        # mirrored into its numeric ring (one attribute load + one O(1)
        # append — the black box sees dispatch cadence without a
        # second instrumentation point in the dispatch loops)
        self.flight = None

    # ------------------------------------------------------- windows

    def begin(self, n_msgs: int, source: str = "publish"
              ) -> Optional[WindowRecord]:
        if not self.enabled:
            return None
        with self._ring_lock:
            self._seq += 1
            seq = self._seq
        return WindowRecord(seq, n_msgs, source)

    def commit(self, rec: WindowRecord) -> None:
        """Fold a finished window into the histograms (ONE lock for
        every stage sample + the e2e batch) and the ring."""
        hist = self._hist
        with self._hlock:
            for name, _off, dur in rec.spans:
                h = hist.get(name)
                if h is None:
                    h = hist[name] = Histogram(lock=self._hlock)
                h._record_locked(dur * 1e6)
            for name, dur in rec.subs:
                h = hist.get(name)
                if h is None:
                    h = hist[name] = Histogram(lock=self._hlock)
                h._record_locked(dur * 1e6)
            if rec.e2e_ms:
                e2e = hist["e2e"]
                for v in rec.e2e_ms:
                    e2e._record_locked(v * 1e3)  # ms -> µs
        with self._ring_lock:
            self._ring[rec.seq % len(self._ring)] = rec
        fl = self.flight
        if fl is not None:
            fl.on_window(rec)

    # -------------------------------------------------- stages/events

    def stage(self, name: str, dur_s: float) -> None:
        """One standalone stage sample (engine-internal stages like
        tokenize that cannot ride a WindowRecord across the engine
        API boundary)."""
        if not self.enabled:
            return
        with self._hlock:
            h = self._hist.get(name)
            if h is None:
                h = self._hist[name] = Histogram(lock=self._hlock)
            h._record_locked(dur_s * 1e6)

    def event(self, kind: str, dur_s: float, **meta) -> None:
        """Engine lifecycle event (XLA compile, device_put transfer,
        delta fold): histogrammed under ``engine_<kind>`` and kept in
        the event ring for the trace export.  Called from builder /
        fold daemon threads."""
        if not self.enabled:
            return
        self.stage("engine_" + kind, dur_s)
        self._events.append((kind, time.time(), dur_s, meta))

    # ---------------------------------------------------- exposition

    def snapshots(self) -> Dict[str, HistogramSnapshot]:
        """Name -> snapshot for every histogram that saw samples,
        pre-created stage families included even when empty (stable
        scrape shape)."""
        with self._hlock:
            items = list(self._hist.items())
        out = {}
        for name, h in items:
            out[name] = h.snapshot()
        return out

    def summary(self) -> Dict[str, Dict[str, object]]:
        return {
            name: snap.to_dict()
            for name, snap in self.snapshots().items()
            if snap.count or name in self.STAGES
        }

    def windows(self, limit: int = 64) -> List[Dict[str, object]]:
        """Most recent committed windows, newest first."""
        return [r.to_dict() for r in self._recent(limit)]

    def _recent(self, limit: int) -> List[WindowRecord]:
        with self._ring_lock:
            recs = [r for r in self._ring if r is not None]
        recs.sort(key=lambda r: r.seq, reverse=True)
        return recs[: max(limit, 0)]

    def events(self, limit: int = 64) -> List[Dict[str, object]]:
        if limit <= 0:
            return []
        out = [
            {"kind": k, "at": ts, "dur_ms": round(d * 1e3, 3), **meta}
            for k, ts, d, meta in list(self._events)
        ]
        return out[-limit:][::-1]

    def reset(self) -> None:
        with self._hlock:
            for h in self._hist.values():
                h._counts = [0] * N_BUCKETS
                h._sum = 0.0
                h._count = 0
        with self._ring_lock:
            self._ring = [None] * len(self._ring)
        self._events.clear()

    # -------------------------------------------------- chrome trace

    def chrome_trace(self, limit: Optional[int] = None) -> Dict[str, object]:
        """The flight recorder as Chrome trace-event JSON (the format
        Perfetto and chrome://tracing load natively): every window is
        its own thread track with paired B/E events per stage (windows
        pipeline, so tracks may overlap in time — per-track events
        stay strictly nested), engine lifecycle events ride tid 0 as
        complete ("X") events."""
        recs = self._recent(limit if limit is not None else len(self._ring))
        recs.reverse()  # oldest first: ts ordering within each track
        engine_events = list(self._events)
        # export timestamps RELATIVE to the trace's own epoch: at
        # absolute epoch-µs magnitude (1.7e15) a float64 has ~0.25 µs
        # of quantization, enough to flip adjacent span edges out of
        # order; small relative values keep full sub-µs precision
        starts = [r.wall0 for r in recs] + [
            ts - dur for _k, ts, dur, _m in engine_events
        ]
        epoch = min(starts) if starts else 0.0
        pid = self.pid
        events: List[Dict[str, object]] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": (
                 f"emqx_tpu window pipeline [{self.process_label} "
                 f"pid={pid}]"
             )}},
            {"name": "process_sort_index", "ph": "M", "pid": pid,
             "tid": 0, "args": {"sort_index": pid}},
        ]
        for rec in recs:
            tid = rec.seq
            base_us = (rec.wall0 - epoch) * 1e6
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"window {rec.seq} ({rec.source})"},
            })
            cursor = base_us  # monotonic clamp: contiguous span
            # offsets are measured independently, so edge timestamps
            # can disagree by an ulp — never let E(k) > B(k+1)
            for name, off, dur in rec.spans:
                b_ts = max(base_us + off * 1e6, cursor)
                e_ts = b_ts + max(dur, 0.0) * 1e6
                cursor = e_ts
                args = {
                    "n_msgs": rec.n_msgs,
                    "path": rec.path,
                    "breaker_open": rec.breaker_open,
                }
                events.append({
                    "name": name, "ph": "B", "pid": pid, "tid": tid,
                    "ts": b_ts, "args": args,
                })
                events.append({
                    "name": name, "ph": "E", "pid": pid, "tid": tid,
                    "ts": e_ts,
                })
        for kind, ts, dur, meta in engine_events:
            events.append({
                "name": kind, "ph": "X", "pid": pid, "tid": 0,
                "ts": (ts - dur - epoch) * 1e6, "dur": dur * 1e6,
                "args": dict(meta),
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ------------------------------------------------- prometheus helpers

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Sanitize a dotted metric name into a valid Prometheus metric
    name: ``.``/``-`` and anything else outside [a-zA-Z0-9_:] become
    ``_``, and a leading digit gets a ``_`` prefix (counter names like
    ``5xx.responses`` would otherwise emit an unparseable family)."""
    out = _PROM_BAD.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def prom_histogram_lines(
    family: str, snap: HistogramSnapshot, help_text: str = ""
) -> List[str]:
    """One Prometheus text-format histogram family: cumulative
    ``_bucket`` samples with ``le`` labels, then ``_sum``/``_count``."""
    lines = [
        f"# HELP {family} {help_text or family}",
        f"# TYPE {family} histogram",
    ]
    cum = 0
    for i, c in enumerate(snap.counts):
        cum += c
        le = str(BOUNDS[i]) if i < len(BOUNDS) else "+Inf"
        lines.append(f'{family}_bucket{{le="{le}"}} {cum}')
    lines.append(f"{family}_sum {snap.sum}")
    lines.append(f"{family}_count {snap.count}")
    return lines
