"""OpenTelemetry export — OTLP/JSON over HTTP, no SDK dependency.

The `emqx_opentelemetry` role (/root/reference/apps/emqx_opentelemetry/
src/emqx_otel_metrics.erl periodic metric push, emqx_otel_logger.erl
log bridge): broker counters/gauges go out as OTLP `resourceMetrics`
to ``{endpoint}/v1/metrics`` on an interval, and (optionally) log
records as OTLP `resourceLogs` to ``{endpoint}/v1/logs``.

OTLP/HTTP has a stable JSON encoding (the protobuf JSON mapping), so a
collector ingests these payloads natively — the environment just has
no otel SDK, and none is needed for export.  Delivery rides the same
buffered resource layer as every other sink: an unreachable collector
never affects the broker.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

from .resources import BufferWorker, HttpSink

_SEVERITY = {  # python level -> OTLP severityNumber
    logging.DEBUG: 5,
    logging.INFO: 9,
    logging.WARNING: 13,
    logging.ERROR: 17,
    logging.CRITICAL: 21,
}


def _attrs(d: Dict[str, str]) -> List[dict]:
    return [
        {"key": k, "value": {"stringValue": str(v)}} for k, v in d.items()
    ]


class OtelExporter:
    """Periodic OTLP metric push + optional log bridge for one broker."""

    def __init__(
        self,
        broker,
        endpoint: str,  # e.g. http://collector:4318
        interval: float = 10.0,
        export_logs: bool = False,
        log_level: int = logging.WARNING,
    ) -> None:
        self.broker = broker
        self.endpoint = endpoint.rstrip("/")
        self.interval = interval
        self.export_logs = export_logs
        self.log_level = log_level
        self._metrics_worker: Optional[BufferWorker] = None
        self._logs_worker: Optional[BufferWorker] = None
        self._handler: Optional[logging.Handler] = None
        self._last: float = 0.0
        self._resource = {
            "attributes": _attrs({
                "service.name": "emqx_tpu",
                "service.instance.id": broker.config.node_name,
            })
        }

    # ------------------------------------------------------ lifecycle

    async def start(self) -> None:
        import asyncio

        self._loop = asyncio.get_running_loop()
        self._metrics_worker = BufferWorker(
            HttpSink(self.endpoint + "/v1/metrics",
                     headers={"Content-Type": "application/json"}),
            max_buffer=64,
            max_retries=3,
        )
        await self._metrics_worker.start()
        if self.export_logs:
            self._logs_worker = BufferWorker(
                HttpSink(self.endpoint + "/v1/logs",
                         headers={"Content-Type": "application/json"}),
                max_buffer=256,
                max_retries=3,
            )
            await self._logs_worker.start()
            self._handler = _OtelLogHandler(self)
            self._handler.setLevel(self.log_level)
            logging.getLogger("emqx_tpu").addHandler(self._handler)

    async def stop(self) -> None:
        if self._handler is not None:
            logging.getLogger("emqx_tpu").removeHandler(self._handler)
            self._handler = None
        if self._metrics_worker is not None:
            await self._metrics_worker.stop()
            self._metrics_worker = None
        if self._logs_worker is not None:
            await self._logs_worker.stop()
            self._logs_worker = None

    # -------------------------------------------------------- metrics

    def tick(self, now: Optional[float] = None) -> bool:
        """Called from the broker's 1 Hz housekeeping; exports every
        ``interval`` seconds.  Returns True when a push was queued."""
        now = time.time() if now is None else now
        if now - self._last < self.interval:
            return False
        self._last = now
        if self._metrics_worker is not None:
            self._metrics_worker.enqueue(self.metrics_payload(now))
            return True
        return False

    def metrics_payload(self, now: float) -> bytes:
        t_ns = str(int(now * 1e9))
        metrics = []
        for name, val in sorted(self.broker.metrics.all().items()):
            metrics.append({
                "name": "emqx_" + name.replace(".", "_"),
                "sum": {
                    "dataPoints": [{"timeUnixNano": t_ns,
                                    "asInt": str(int(val))}],
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "isMonotonic": True,
                },
            })
        for name, val in sorted(self.broker.stats.all().items()):
            metrics.append({
                "name": "emqx_" + name.replace(".", "_"),
                "gauge": {
                    "dataPoints": [{"timeUnixNano": t_ns,
                                    "asInt": str(int(val))}],
                },
            })
        return json.dumps({
            "resourceMetrics": [{
                "resource": self._resource,
                "scopeMetrics": [{
                    "scope": {"name": "emqx_tpu"},
                    "metrics": metrics,
                }],
            }]
        }).encode()

    # ----------------------------------------------------------- logs

    def emit_log(self, record: logging.LogRecord) -> None:
        if self._logs_worker is None:
            return
        # the buffer worker itself logs drops/outages on
        # emqx_tpu.resources — exporting those would regenerate one
        # query per drop against a dead collector, forever
        if record.name.startswith("emqx_tpu.resources"):
            return
        body = {
            "resourceLogs": [{
                "resource": self._resource,
                "scopeLogs": [{
                    "scope": {"name": record.name},
                    "logRecords": [{
                        "timeUnixNano": str(int(record.created * 1e9)),
                        "severityNumber": _SEVERITY.get(
                            record.levelno,
                            min(21, max(1, record.levelno // 5)),
                        ),
                        "severityText": record.levelname,
                        "body": {"stringValue": record.getMessage()},
                        "attributes": _attrs({
                            "logger": record.name,
                            "module": record.module,
                        }),
                    }],
                }],
            }]
        }
        # logs can arrive from worker threads (engine fold/build
        # daemons); BufferWorker wakes an asyncio.Event, which must
        # happen on the loop thread
        self._loop.call_soon_threadsafe(
            self._logs_worker.enqueue, json.dumps(body).encode()
        )


class _OtelLogHandler(logging.Handler):
    def __init__(self, exporter: OtelExporter) -> None:
        super().__init__()
        self.exporter = exporter

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.exporter.emit_log(record)
        except Exception:  # never let telemetry break logging
            pass
