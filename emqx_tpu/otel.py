"""OpenTelemetry export — OTLP/JSON over HTTP, no SDK dependency.

The `emqx_opentelemetry` role (/root/reference/apps/emqx_opentelemetry/
src/emqx_otel_metrics.erl periodic metric push, emqx_otel_logger.erl
log bridge, emqx_otel_trace.erl distributed spans behind the
emqx_external_trace behavior): broker counters/gauges go out as OTLP
`resourceMetrics` to ``{endpoint}/v1/metrics`` on an interval,
(optionally) log records as OTLP `resourceLogs` to
``{endpoint}/v1/logs``, and (optionally) TRACE SPANS — one
``message.publish`` span per routed message with child
``message.deliver`` spans per receiving client — as OTLP
`resourceSpans` to ``{endpoint}/v1/traces``, with W3C ``traceparent``
context extracted from / injected into MQTT 5 user properties so a
publisher's trace continues through the broker to every subscriber
(emqx_channel.erl:439-443's trace hooks).

OTLP/HTTP has a stable JSON encoding (the protobuf JSON mapping), so a
collector ingests these payloads natively — the environment just has
no otel SDK, and none is needed for export.  Delivery rides the same
buffered resource layer as every other sink: an unreachable collector
never affects the broker.
"""

from __future__ import annotations

import json
import logging
import random
import secrets
import time
from typing import Any, Dict, List, Optional

from .resources import BufferWorker, HttpSink

_SEVERITY = {  # python level -> OTLP severityNumber
    logging.DEBUG: 5,
    logging.INFO: 9,
    logging.WARNING: 13,
    logging.ERROR: 17,
    logging.CRITICAL: 21,
}


def _attrs(d: Dict[str, str]) -> List[dict]:
    return [
        {"key": k, "value": {"stringValue": str(v)}} for k, v in d.items()
    ]


def lifecycle_span_json(d: Dict) -> Dict:
    """A lifecycle-tracer span dict (tracecontext.py shape) as OTLP
    JSON — span events included, so the window's stage boundaries and
    failpoint hits arrive at the collector attached to the span."""
    out = {
        "traceId": d["trace_id"],
        "spanId": d["span_id"],
        "name": d["name"],
        "kind": 1,  # INTERNAL: broker pipeline stages
        "startTimeUnixNano": str(d["start_ns"]),
        "endTimeUnixNano": str(d["end_ns"]),
        "attributes": _attrs({
            **d.get("attrs", {}),
            "node": d.get("node", ""),
            "mid": d.get("mid", ""),
        }),
    }
    if d.get("parent_id"):
        out["parentSpanId"] = d["parent_id"]
    events = d.get("events")
    if events:
        out["events"] = [
            {
                "timeUnixNano": str(e["ts_ns"]),
                "name": e["name"],
                "attributes": _attrs(e.get("attrs", {})),
            }
            for e in events
        ]
    return out


class Span:
    """One in-flight span; finished spans serialize to the OTLP JSON
    span shape."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start_ns", "end_ns", "attrs", "kind")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 attrs: Dict[str, Any], kind: int) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attrs = attrs
        self.kind = kind

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_json(self) -> Dict:
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "name": self.name,
            "kind": self.kind,
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns or time.time_ns()),
            "attributes": _attrs(self.attrs),
        }
        if self.parent_id:
            out["parentSpanId"] = self.parent_id
        return out


class Tracer:
    """The span factory + batcher: finished spans accumulate and flush
    through the exporter's traces worker.  Sampling: an upstream
    ``traceparent`` is always honored (the publisher opted the message
    in); root spans sample at ``sample_ratio``."""

    USER_PROP_KEY = "traceparent"

    def __init__(self, sample_ratio: float = 1.0,
                 flush_at: int = 64) -> None:
        self.sample_ratio = sample_ratio
        self.flush_at = flush_at
        self._done: List[Span] = []
        self.on_flush = None  # set by the exporter
        self.stats = {"spans": 0, "sampled_out": 0}

    # ------------------------------------------------------ context

    @classmethod
    def extract(cls, properties: Dict) -> Optional[str]:
        """W3C traceparent from MQTT 5 user properties."""
        for k, v in properties.get("user_property", ()) or ():
            if k == cls.USER_PROP_KEY:
                return v
        return None

    @classmethod
    def inject(cls, properties: Dict, span: "Span") -> None:
        ups = [
            (k, v)
            for k, v in (properties.get("user_property", ()) or ())
            if k != cls.USER_PROP_KEY
        ]
        ups.append((cls.USER_PROP_KEY, span.traceparent))
        properties["user_property"] = ups

    # -------------------------------------------------------- spans

    def start(self, name: str, parent: Optional[Any] = None,
              attrs: Optional[Dict] = None,
              kind: int = 1) -> Optional[Span]:
        trace_id = parent_id = None
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, str):
            try:  # "00-<32 hex>-<16 hex>-<flags>"
                _, trace_id, parent_id, _ = parent.split("-")
            except ValueError:
                parent = None
        if parent is None and random.random() >= self.sample_ratio:
            self.stats["sampled_out"] += 1
            return None
        return Span(
            trace_id or secrets.token_hex(16),
            secrets.token_hex(8),
            parent_id, name, dict(attrs or ()), kind,
        )

    def end(self, span: Optional[Span]) -> None:
        if span is None:
            return
        span.end_ns = time.time_ns()
        self._done.append(span)
        self.stats["spans"] += 1
        if len(self._done) >= self.flush_at:
            self.flush()

    def flush(self) -> None:
        if self._done and self.on_flush is not None:
            spans, self._done = self._done, []
            try:
                self.on_flush(spans)
            except Exception:
                pass


class OtelExporter:
    """Periodic OTLP metric push + optional log bridge + optional
    span pipeline for one broker."""

    def __init__(
        self,
        broker,
        endpoint: str,  # e.g. http://collector:4318
        interval: float = 10.0,
        export_logs: bool = False,
        log_level: int = logging.WARNING,
        export_traces: bool = False,
        trace_sample_ratio: float = 1.0,
    ) -> None:
        self.broker = broker
        self.endpoint = endpoint.rstrip("/")
        self.interval = interval
        self.export_logs = export_logs
        self.log_level = log_level
        self.export_traces = export_traces
        self.tracer: Optional[Tracer] = (
            Tracer(sample_ratio=trace_sample_ratio)
            if export_traces else None
        )
        self._metrics_worker: Optional[BufferWorker] = None
        self._logs_worker: Optional[BufferWorker] = None
        self._traces_worker: Optional[BufferWorker] = None
        self._lc_pending: List[Dict] = []  # lifecycle spans awaiting flush
        self._handler: Optional[logging.Handler] = None
        self._last: float = 0.0
        self._resource = {
            "attributes": _attrs({
                "service.name": "emqx_tpu",
                "service.instance.id": broker.config.node_name,
            })
        }

    # ------------------------------------------------------ lifecycle

    async def start(self) -> None:
        import asyncio

        self._loop = asyncio.get_running_loop()
        self._metrics_worker = BufferWorker(
            HttpSink(self.endpoint + "/v1/metrics",
                     headers={"Content-Type": "application/json"}),
            max_buffer=64,
            max_retries=3,
        )
        await self._metrics_worker.start()
        if self.export_logs:
            self._logs_worker = BufferWorker(
                HttpSink(self.endpoint + "/v1/logs",
                         headers={"Content-Type": "application/json"}),
                max_buffer=256,
                max_retries=3,
            )
            await self._logs_worker.start()
            self._handler = _OtelLogHandler(self)
            self._handler.setLevel(self.log_level)
            logging.getLogger("emqx_tpu").addHandler(self._handler)
        if self.tracer is not None:
            self._traces_worker = BufferWorker(
                HttpSink(self.endpoint + "/v1/traces",
                         headers={"Content-Type": "application/json"}),
                max_buffer=256,
                max_retries=3,
            )
            await self._traces_worker.start()
            self.tracer.on_flush = self._flush_spans
            # the broker's publish/dispatch path consults this handle
            self.broker.tracer = self.tracer
            # lifecycle-tracer spans (tracecontext.py) flow out through
            # the SAME traces worker: the in-process store serves local
            # queries, the collector gets the distributed picture
            lifecycle = getattr(self.broker, "lifecycle", None)
            if lifecycle is not None:
                lifecycle.on_export = self._export_lifecycle

    async def stop(self) -> None:
        if self._handler is not None:
            logging.getLogger("emqx_tpu").removeHandler(self._handler)
            self._handler = None
        if self.tracer is not None:
            self.broker.tracer = None
            self.tracer.flush()
            lifecycle = getattr(self.broker, "lifecycle", None)
            if lifecycle is not None and \
                    lifecycle.on_export == self._export_lifecycle:
                lifecycle.on_export = None
            self._flush_lifecycle()
        if self._metrics_worker is not None:
            await self._metrics_worker.stop()
            self._metrics_worker = None
        if self._logs_worker is not None:
            await self._logs_worker.stop()
            self._logs_worker = None
        if self._traces_worker is not None:
            await self._traces_worker.stop()
            self._traces_worker = None

    def _flush_spans(self, spans: List[Span]) -> None:
        if self._traces_worker is None:
            return
        self._enqueue_span_json([s.to_json() for s in spans])

    def _enqueue_span_json(self, spans: List[Dict]) -> None:
        body = json.dumps({
            "resourceSpans": [{
                "resource": self._resource,
                "scopeSpans": [{
                    "scope": {"name": "emqx_tpu"},
                    "spans": spans,
                }],
            }]
        }).encode()
        self._traces_worker.enqueue(body)

    def _export_lifecycle(self, span: Dict) -> None:
        """LifecycleTracer.on_export target: batch finished lifecycle
        spans and flush them with the ordinary span cadence (size
        threshold here, the 1 Hz tick below bounds latency)."""
        self._lc_pending.append(lifecycle_span_json(span))
        if len(self._lc_pending) >= 64:
            self._flush_lifecycle()

    def _flush_lifecycle(self) -> None:
        if self._lc_pending and self._traces_worker is not None:
            pending, self._lc_pending = self._lc_pending, []
            try:
                self._enqueue_span_json(pending)
            except Exception:
                pass  # export must never affect dispatch

    # -------------------------------------------------------- metrics

    def tick(self, now: Optional[float] = None) -> bool:
        """Called from the broker's 1 Hz housekeeping; exports every
        ``interval`` seconds.  Returns True when a push was queued."""
        now = time.time() if now is None else now
        if self.tracer is not None:
            self.tracer.flush()  # bound span latency to the tick
            self._flush_lifecycle()
        if now - self._last < self.interval:
            return False
        self._last = now
        if self._metrics_worker is not None:
            self._metrics_worker.enqueue(self.metrics_payload(now))
            return True
        return False

    def metrics_payload(self, now: float) -> bytes:
        t_ns = str(int(now * 1e9))
        start_ns = str(int(self.broker.metrics.start_time * 1e9))
        metrics = []
        for name, val in sorted(self.broker.metrics.all().items()):
            metrics.append({
                "name": "emqx_" + name.replace(".", "_"),
                "sum": {
                    "dataPoints": [{"timeUnixNano": t_ns,
                                    "asInt": str(int(val))}],
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "isMonotonic": True,
                },
            })
        for name, val in sorted(self.broker.stats.all().items()):
            metrics.append({
                "name": "emqx_" + name.replace(".", "_"),
                "gauge": {
                    "dataPoints": [{"timeUnixNano": t_ns,
                                    "asInt": str(int(val))}],
                },
            })
        # engine gauge surface (index tiers, auto-policy, breaker,
        # cost EWMAs) — MatchEngine.stats(), floats as asDouble
        for name, val in sorted(self.broker.router.engine.stats().items()):
            if val is None:
                continue
            if isinstance(val, bool):
                val = int(val)
            if not isinstance(val, (int, float)):
                continue
            dp: Dict[str, Any] = {"timeUnixNano": t_ns}
            if isinstance(val, float):
                dp["asDouble"] = val
            else:
                dp["asInt"] = str(val)
            metrics.append({
                "name": "emqx_engine_" + name.replace(".", "_"),
                "gauge": {"dataPoints": [dp]},
            })
        # multicore shm window-ring occupancy (the same surface the
        # flight recorder samples as EV_RING events), as live gauges
        svc_info = getattr(self.broker.router.engine, "service_info",
                           None)
        if svc_info is not None:
            ring = (svc_info() or {}).get("ring") or {}
            for name, val in sorted(ring.items()):
                if not isinstance(val, (int, float)) or isinstance(
                    val, bool
                ):
                    continue
                metrics.append({
                    "name": "emqx_multicore_ring_"
                            + str(name).replace(".", "_"),
                    "gauge": {"dataPoints": [{
                        "timeUnixNano": t_ns, "asInt": str(int(val)),
                    }]},
                })
        # window profiler stage histograms as OTLP histogram
        # datapoints (per-bucket counts + explicit log2 bounds)
        prof = getattr(self.broker, "profiler", None)
        if prof is not None and prof.enabled:
            from .observability import BOUNDS

            bounds = list(BOUNDS)
            for name, snap in sorted(prof.snapshots().items()):
                if not snap.count:
                    continue
                metrics.append({
                    "name": f"emqx_profiler_{name}_us",
                    "unit": "us",
                    "histogram": {
                        "dataPoints": [{
                            "startTimeUnixNano": start_ns,
                            "timeUnixNano": t_ns,
                            "count": str(snap.count),
                            "sum": snap.sum,
                            "bucketCounts": [
                                str(c) for c in snap.counts
                            ],
                            "explicitBounds": bounds,
                        }],
                        "aggregationTemporality": 2,  # CUMULATIVE
                    },
                })
        return json.dumps({
            "resourceMetrics": [{
                "resource": self._resource,
                "scopeMetrics": [{
                    "scope": {"name": "emqx_tpu"},
                    "metrics": metrics,
                }],
            }]
        }).encode()

    # ----------------------------------------------------------- logs

    def emit_log(self, record: logging.LogRecord) -> None:
        if self._logs_worker is None:
            return
        # the buffer worker itself logs drops/outages on
        # emqx_tpu.resources — exporting those would regenerate one
        # query per drop against a dead collector, forever
        if record.name.startswith("emqx_tpu.resources"):
            return
        body = {
            "resourceLogs": [{
                "resource": self._resource,
                "scopeLogs": [{
                    "scope": {"name": record.name},
                    "logRecords": [{
                        "timeUnixNano": str(int(record.created * 1e9)),
                        "severityNumber": _SEVERITY.get(
                            record.levelno,
                            min(21, max(1, record.levelno // 5)),
                        ),
                        "severityText": record.levelname,
                        "body": {"stringValue": record.getMessage()},
                        "attributes": _attrs({
                            "logger": record.name,
                            "module": record.module,
                        }),
                    }],
                }],
            }]
        }
        # logs can arrive from worker threads (engine fold/build
        # daemons); BufferWorker wakes an asyncio.Event, which must
        # happen on the loop thread
        self._loop.call_soon_threadsafe(
            self._logs_worker.enqueue, json.dumps(body).encode()
        )


class _OtelLogHandler(logging.Handler):
    def __init__(self, exporter: OtelExporter) -> None:
        super().__init__()
        self.exporter = exporter

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.exporter.emit_log(record)
        except Exception:  # never let telemetry break logging
            pass
