"""CoAP gateway (RFC 7252) over UDP — publish/subscribe bridge.

Capability match for the reference's CoAP gateway
(/root/reference/apps/emqx_gateway_coap/src/emqx_coap_frame.erl wire
codec, emqx_coap_pubsub_handler.erl): connectionless mode where

  * ``PUT``/``POST coap://host/ps/{topic}?qos=&retain=`` publishes,
  * ``GET /ps/{topic}`` with ``Observe: 0`` subscribes (topic may hold
    ``+``/``#`` wildcards), ``Observe: 1`` unsubscribes,
  * matched broker deliveries flow back as ``2.05 Content``
    notifications carrying the subscribe token and a growing Observe
    sequence number,
  * ``clientid``/``username``/``password`` ride Uri-Query (the
    reference's connectionless auth shape).

One channel per UDP peer; the channel opens a broker session lazily on
the first request and reuses the shared micro-batcher for publishes."""

from __future__ import annotations

import logging
import secrets
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import topic as T
from ..access import PUBLISH, SUBSCRIBE, ClientInfo
from ..codec import mqtt as C
from ..message import Message
from ..broker.session import SubOpts
from . import GatewayChannel, GatewayFrame, UdpGateway

log = logging.getLogger("emqx_tpu.gateway.coap")

# message types
CON, NON, ACK, RST = 0, 1, 2, 3

# method / response codes: class << 5 | detail
GET, POST, PUT, DELETE = 0x01, 0x02, 0x03, 0x04
CREATED = 0x41  # 2.01
DELETED = 0x42  # 2.02
VALID = 0x43  # 2.03
CHANGED = 0x44  # 2.04
CONTENT = 0x45  # 2.05
CONTINUE = 0x5F  # 2.31 (RFC 7959)
BAD_REQUEST = 0x80  # 4.00
UNAUTHORIZED = 0x81  # 4.01
NOT_FOUND = 0x84  # 4.04
ENTITY_INCOMPLETE = 0x88  # 4.08 (RFC 7959)
ENTITY_TOO_LARGE = 0x8D  # 4.13

# option numbers
OPT_OBSERVE = 6
OPT_URI_PATH = 11
OPT_CONTENT_FORMAT = 12
OPT_URI_QUERY = 15
OPT_BLOCK1 = 27  # RFC 7959 request-payload blockwise transfer


def _parse_block(v: bytes) -> Tuple[int, bool, int]:
    """Block option value -> (num, more, szx); empty = block 0."""
    n = int.from_bytes(v, "big") if v else 0
    return n >> 4, bool(n & 0x08), n & 0x07


@dataclass
class CoapMessage:
    type: int = CON
    code: int = GET
    message_id: int = 0
    token: bytes = b""
    options: List[Tuple[int, bytes]] = field(default_factory=list)
    payload: bytes = b""

    def opt_all(self, num: int) -> List[bytes]:
        return [v for n, v in self.options if n == num]

    def opt(self, num: int) -> Optional[bytes]:
        vals = self.opt_all(num)
        return vals[0] if vals else None

    @property
    def uri_path(self) -> List[str]:
        return [v.decode("utf-8", "replace") for v in
                self.opt_all(OPT_URI_PATH)]

    @property
    def queries(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for v in self.opt_all(OPT_URI_QUERY):
            s = v.decode("utf-8", "replace")
            k, _, val = s.partition("=")
            out[k] = val
        return out

    @property
    def observe(self) -> Optional[int]:
        v = self.opt(OPT_OBSERVE)
        if v is None:
            return None
        return int.from_bytes(v, "big") if v else 0


def _encode_uint(n: int) -> bytes:
    if n == 0:
        return b""
    out = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return out


class CoapCodec(GatewayFrame):
    """RFC 7252 §3 framing: one datagram = one message."""

    def parse(self, state, data: bytes) -> Tuple[List[CoapMessage], object]:
        if len(data) < 4:
            raise ValueError("short CoAP datagram")
        b0 = data[0]
        if (b0 >> 6) != 1:
            raise ValueError(f"bad CoAP version {b0 >> 6}")
        mtype = (b0 >> 4) & 0x03
        tkl = b0 & 0x0F
        if tkl > 8:
            raise ValueError("token too long")
        code = data[1]
        mid = struct.unpack_from(">H", data, 2)[0]
        off = 4
        token = data[off : off + tkl]
        off += tkl
        options: List[Tuple[int, bytes]] = []
        num = 0
        payload = b""
        while off < len(data):
            b = data[off]
            off += 1
            if b == 0xFF:
                payload = data[off:]
                break
            delta, length = b >> 4, b & 0x0F
            if delta == 13:
                delta = 13 + data[off]; off += 1
            elif delta == 14:
                delta = 269 + struct.unpack_from(">H", data, off)[0]; off += 2
            elif delta == 15:
                raise ValueError("reserved option delta 15")
            if length == 13:
                length = 13 + data[off]; off += 1
            elif length == 14:
                length = 269 + struct.unpack_from(">H", data, off)[0]; off += 2
            elif length == 15:
                raise ValueError("reserved option length 15")
            num += delta
            options.append((num, data[off : off + length]))
            off += length
        return [CoapMessage(mtype, code, mid, token, options, payload)], state

    def serialize(self, m: CoapMessage) -> bytes:
        out = bytearray()
        out.append(0x40 | (m.type << 4) | len(m.token))
        out.append(m.code)
        out += struct.pack(">H", m.message_id)
        out += m.token
        last = 0
        for num, val in sorted(m.options, key=lambda o: o[0]):
            delta = num - last
            last = num
            d_ext = l_ext = b""
            if delta >= 269:
                d_nib, d_ext = 14, struct.pack(">H", delta - 269)
            elif delta >= 13:
                d_nib, d_ext = 13, bytes([delta - 13])
            else:
                d_nib = delta
            length = len(val)
            if length >= 269:
                l_nib, l_ext = 14, struct.pack(">H", length - 269)
            elif length >= 13:
                l_nib, l_ext = 13, bytes([length - 13])
            else:
                l_nib = length
            out.append((d_nib << 4) | l_nib)
            out += d_ext + l_ext + val
        if m.payload:
            out.append(0xFF)
            out += m.payload
        return bytes(out)


class CoapChannel(GatewayChannel):
    """Connectionless pub/sub handler (emqx_coap_pubsub_handler.erl)."""

    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.codec: CoapCodec = gateway.frame
        self.client: Optional[ClientInfo] = None
        self._next_mid = secrets.randbelow(0xFFFF)
        # observe registrations: filter -> (token, next sequence number)
        self._observers: Dict[str, Tuple[bytes, int]] = {}
        # recent notification message id -> filter, so an RST cancels
        # only the observation it responds to (RFC 7641 §3.6)
        self._note_mids: Dict[int, str] = {}
        # Block1 assembly buffers: (token, topic) -> partial payload,
        # charged against the GATEWAY-wide budget (spoofed sources can
        # mint channels freely, so per-channel caps alone don't bound
        # memory); completed transfers remembered for dup final blocks
        self._block_bufs: Dict[Tuple[bytes, str], bytearray] = {}
        self._block_done: Dict[Tuple[bytes, str], int] = {}

    def _blk_charge(self, n: int) -> bool:
        gw = self.gateway
        if gw._block_total + n > gw.block_budget:
            return False
        gw._block_total += n
        return True

    def _blk_credit(self, n: int) -> None:
        self.gateway._block_total -= n

    def _blk_drop(self, key) -> None:
        buf = self._block_bufs.pop(key, None)
        if buf is not None:
            self._blk_credit(len(buf))

    def connection_lost(self, reason: str) -> None:
        for key in list(self._block_bufs):
            self._blk_drop(key)
        super().connection_lost(reason)

    def _alloc_mid(self) -> int:
        self._next_mid = (self._next_mid + 1) % 0x10000
        return self._next_mid

    def _reply(self, req: CoapMessage, code: int,
               options: Optional[List[Tuple[int, bytes]]] = None,
               payload: bytes = b"") -> None:
        # piggy-backed ACK for CON, NON reply for NON (RFC 7252 §5.2)
        if req.type == CON:
            rtype, mid = ACK, req.message_id
        else:
            rtype, mid = NON, self._alloc_mid()
        self.write(self.codec.serialize(CoapMessage(
            rtype, code, mid, req.token, options or [], payload)))

    # --------------------------------------------------------- session

    def _ensure_session(self, req: CoapMessage) -> bool:
        if self.session is not None:
            return True
        q = req.queries
        clientid = q.get("clientid") or "coap-" + secrets.token_hex(4)
        client = ClientInfo(
            clientid=clientid,
            username=q.get("username"),
            password=(q.get("password") or "").encode() or None,
            peerhost=self.peer,
        )
        if self.broker.banned.is_banned(
            clientid=clientid, username=client.username,
            peerhost=self.peer.rsplit(":", 1)[0],
        ):
            return False
        ok, client = self.broker.access.authenticate(client)
        if not ok:
            return False
        client.password = None
        self.client = client
        self.open_session(clientid, clean_start=True)
        return True

    # ------------------------------------------------------ frame pump

    def handle_frame(self, m: CoapMessage) -> None:
        if m.type == RST:
            # observe cancel via reset (RFC 7641 §3.6): only the
            # observation whose notification was rejected — an RST is
            # spoofable, so it must never be a kill-all
            flt = self._note_mids.pop(m.message_id, None)
            if flt is not None:
                self._cancel_observe(flt)
            return
        if m.type == ACK or m.code == 0:  # ack / empty ping
            if m.type == CON and m.code == 0:
                self.write(self.codec.serialize(CoapMessage(
                    RST, 0, m.message_id, b"")))
            return
        path = m.uri_path
        if not path or path[0] != "ps":
            self._reply(m, NOT_FOUND)
            return
        topic = "/".join(path[1:])
        if not topic:
            self._reply(m, BAD_REQUEST)
            return
        if not self._ensure_session(m):
            self._reply(m, UNAUTHORIZED)
            return
        if m.code in (PUT, POST):
            self._handle_publish(m, topic)
        elif m.code == GET:
            obs = m.observe
            if obs == 0:
                self._handle_subscribe(m, topic)
            elif obs == 1:
                self._handle_unsubscribe(m, topic)
            else:
                self._reply(m, BAD_REQUEST)
        elif m.code == DELETE:
            self._handle_unsubscribe(m, topic)
        else:
            self._reply(m, BAD_REQUEST)

    def _handle_publish(self, m: CoapMessage, topic: str) -> None:
        if not self.broker.access.authorize(self.client, PUBLISH, topic):
            self._reply(m, UNAUTHORIZED)
            return
        payload = m.payload
        b1 = m.opt(OPT_BLOCK1)
        if b1 is not None:
            # RFC 7959 Block1: a constrained writer streams a large
            # payload in 16..1024-byte blocks; the assembled whole is
            # published once the final (M=0) block lands
            num, more, szx = _parse_block(b1)
            size = 16 << szx
            key = (bytes(m.token), topic)
            buf = self._block_bufs.get(key)
            if buf is not None and len(buf) == (num + 1) * size:
                # duplicate of the last block (our 2.31 ACK was lost
                # and the CON retransmitted): re-ACK, don't re-append
                if more:
                    self._reply(m, CONTINUE, options=[(OPT_BLOCK1, b1)])
                    return
            elif self._block_done.get(key) == num and buf is None:
                # retransmitted FINAL block after the publish: re-ACK
                # without publishing a duplicate
                self._reply(m, CHANGED, options=[(OPT_BLOCK1, b1)])
                return
            elif num == 0:
                if buf is None and len(self._block_bufs) >= 4:
                    self._reply(m, ENTITY_TOO_LARGE)
                    return  # per-peer concurrent-assembly cap
                if buf is not None:
                    self._blk_credit(len(buf))
                buf = self._block_bufs[key] = bytearray()
                self._block_done.pop(key, None)
            elif buf is None or len(buf) != num * size:
                # out-of-order / unknown transfer (§2.5)
                self._blk_drop(key)
                self._reply(m, ENTITY_INCOMPLETE)
                return
            if buf is not None and len(buf) != (num + 1) * size:
                if not self._blk_charge(len(m.payload)):
                    self._blk_drop(key)
                    self._reply(m, ENTITY_TOO_LARGE)
                    return
                buf += m.payload
            if len(buf) > self.broker.config.mqtt.max_packet_size:
                self._blk_drop(key)
                self._reply(m, ENTITY_TOO_LARGE)
                return
            if more:
                self._reply(m, CONTINUE, options=[(OPT_BLOCK1, b1)])
                return
            self._blk_credit(len(buf))
            self._block_bufs.pop(key)
            self._block_done[key] = num
            if len(self._block_done) > 16:
                self._block_done.pop(next(iter(self._block_done)))
            payload = bytes(buf)
        q = m.queries
        try:
            qos = min(max(int(q.get("qos", "0")), 0), 2)
        except ValueError:
            qos = 0
        msg = Message(
            topic=topic, payload=payload, qos=qos,
            retain=q.get("retain") in ("true", "1"),
            from_client=self.clientid,
            from_username=self.client.username if self.client else None,
        )
        self.broker_publish(msg)
        self._reply(
            m, CHANGED,
            options=[(OPT_BLOCK1, b1)] if b1 is not None else None,
        )

    def _handle_subscribe(self, m: CoapMessage, flt: str) -> None:
        if not self.broker.access.authorize(self.client, SUBSCRIBE, flt):
            self._reply(m, UNAUTHORIZED)
            return
        q = m.queries
        try:
            qos = min(max(int(q.get("qos", "0")), 0), 2)
        except ValueError:
            qos = 0
        opts = SubOpts(qos=qos)
        is_new = self.session.subscribe(flt, opts)
        self.broker.subscribe(self.clientid, flt, opts, is_new_sub=is_new)
        self._observers[flt] = (m.token, 1)
        self._reply(m, CONTENT, options=[(OPT_OBSERVE, b"")])

    def _handle_unsubscribe(self, m: CoapMessage, flt: str) -> None:
        self._cancel_observe(flt)
        self._reply(m, DELETED)

    def _cancel_observe(self, flt: str) -> None:
        if flt in self._observers:
            del self._observers[flt]
            if self.session is not None:
                self.session.unsubscribe(flt)
                self.broker.unsubscribe(self.clientid, flt)

    # ----------------------------------------------------- deliveries

    def deliver(self, packets) -> None:
        for pkt in packets:
            if pkt.type != C.PUBLISH:
                continue
            # every matching observe relation gets the notification
            # (overlapping filters behave like overlapping MQTT subs:
            # duplicates are possible, starvation is not)
            for flt, (token, seq) in list(self._observers.items()):
                if not T.match(pkt.topic, flt):
                    continue
                self._observers[flt] = (token, seq + 1)
                mid = self._alloc_mid()
                if len(self._note_mids) >= 512:
                    self._note_mids.clear()
                self._note_mids[mid] = flt
                note = CoapMessage(
                    NON, CONTENT, mid, token,
                    [(OPT_OBSERVE, _encode_uint(seq)),
                     (OPT_URI_PATH, b"ps")],
                    pkt.payload,
                )
                self.write(self.codec.serialize(note))
            # QoS1+ deliveries settle immediately: CoAP NON has no
            # application ack (the reference treats notifications the
            # same way in connectionless mode)
            if pkt.packet_id and self.session is not None:
                _ok, follow = self.session.puback(pkt.packet_id)
                if follow:
                    self.deliver(follow)


class CoapGateway(UdpGateway):
    name = "coap"
    frame_class = CoapCodec
    channel_class = CoapChannel
    # gateway-wide Block1 assembly budget: abandoned transfers from
    # spoofed sources pin at most this much until the idle reaper runs
    block_budget = 32 * 1024 * 1024

    def __init__(self, broker, bind: str = "0.0.0.0",
                 port: int = 0) -> None:
        super().__init__(broker, bind, port)
        self._block_total = 0
