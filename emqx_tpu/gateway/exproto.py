"""exproto gateway: protocol logic lives in an external gRPC service.

The `emqx_gateway_exproto` role (/root/reference/apps/emqx_gateway_exproto/
src/emqx_exproto_channel.erl event flow, priv/protos/exproto.proto
contract): we accept raw TCP connections, forward socket events to the
user's ``ConnectionUnaryHandler`` service (OnSocketCreated /
OnReceivedBytes / OnSocketClosed / OnTimerTimeout / OnReceivedMessages),
and serve ``ConnectionAdapter`` so that service can drive each
connection: send bytes, authenticate a clientid, subscribe/publish on
the broker core, start the keepalive timer, close the socket.

gRPC plumbing mirrors the exhook server: protoc-generated message
classes + hand-wired generic method handlers (no grpc_tools codegen in
this environment); handler->broker calls marshal onto the asyncio loop
with ``call_soon_threadsafe``, and gateway->handler calls use
future-based stubs so the event loop never blocks on the handler
service."""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Tuple

from ..access import PUBLISH as ACT_PUBLISH
from ..access import SUBSCRIBE as ACT_SUBSCRIBE
from ..access import ClientInfo
from ..codec import mqtt as C
from ..message import Message
from ..broker.session import SubOpts
from ..grpc_util import ensure_pb2
from . import Gateway, GatewayChannel, GatewayFrame

log = logging.getLogger("emqx_tpu.gateway.exproto")

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))

ADAPTER_SERVICE = "emqx.exproto.v1.ConnectionAdapter"
HANDLER_SERVICE = "emqx.exproto.v1.ConnectionUnaryHandler"

pb = ensure_pb2(
    os.path.join(_REPO, "proto", "exproto.proto"), _HERE, "exproto_pb2"
)

SUCCESS = 0
UNKNOWN = 1
CONN_PROCESS_NOT_ALIVE = 2
REQUIRED_PARAMS_MISSED = 3
PERMISSION_DENY = 5


class _RawFrame(GatewayFrame):
    """Passthrough: the external handler owns all framing."""

    def parse(self, state, data: bytes):
        return [data], state

    def serialize(self, frame) -> bytes:
        return frame


class ExprotoChannel(GatewayChannel):
    """One raw TCP connection, driven by the external handler."""

    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.conn_id = f"{gateway.node}:{next(gateway._conn_seq)}"
        self.client: Optional[ClientInfo] = None
        self.keepalive_s = 0.0
        self.last_rx = time.monotonic()
        self._keepalive_task: Optional[asyncio.Task] = None
        # per-connection handler-call chain: socket events must reach
        # the handler service in order (created -> bytes... -> closed),
        # and independent gRPC futures into its thread pool would race
        self._call_queue: List[Tuple[str, object]] = []
        self._call_inflight = False
        gateway.conns[self.conn_id] = self
        host, _, port = peer.rpartition(":")
        self.call_handler("OnSocketCreated", pb.SocketCreatedRequest(
            conn=self.conn_id,
            conninfo=pb.ConnInfo(
                socktype=pb.TCP,
                peername=pb.Address(
                    host=host,
                    # peer may be "?" when the socket reset before the
                    # peername could be read
                    port=int(port) if port.isdigit() else 0,
                ),
                sockname=pb.Address(host=gateway.bind, port=gateway.port),
            ),
        ))

    def call_handler(self, method: str, request) -> None:
        """Queue a handler call; at most one in flight per connection,
        issued in arrival order (all entry points run on the loop)."""
        self._call_queue.append((method, request))
        if not self._call_inflight:
            self._pump_calls()

    def _pump_calls(self) -> None:
        if not self._call_queue:
            self._call_inflight = False
            return
        self._call_inflight = True
        method, request = self._call_queue.pop(0)
        loop = self.gateway._loop

        def done(_f):
            if loop is not None and not loop.is_closed():
                loop.call_soon_threadsafe(self._pump_calls)

        self.gateway.call_handler(method, request, on_done=done)

    def handle_frame(self, frame: bytes) -> None:
        self.last_rx = time.monotonic()
        self.call_handler(
            "OnReceivedBytes",
            pb.ReceivedBytesRequest(conn=self.conn_id, bytes=frame),
        )

    def deliver(self, packets) -> None:
        # iterative settle: each puback can dequeue ANOTHER packet from
        # the session's backlog (recursing here would stack one frame
        # per queued message)
        pending = list(packets)
        while pending:
            batch, pending = pending, []
            msgs = [
                pb.Message(
                    node=self.gateway.node,
                    id=pkt.packet_id and str(pkt.packet_id) or "",
                    qos=pkt.qos,
                    topic=pkt.topic,
                    payload=bytes(pkt.payload),
                    timestamp=int(time.time() * 1000),
                )
                for pkt in batch
                if pkt.type == C.PUBLISH
            ]
            if not msgs:
                return
            self.call_handler(
                "OnReceivedMessages",
                pb.ReceivedMessagesRequest(conn=self.conn_id, messages=msgs),
            )
            # the handler owns its wire framing; broker-side QoS1
            # deliveries settle on handoff (the reference treats the
            # handler service as the terminal hop the same way)
            if self.session is not None:
                for pkt in batch:
                    if pkt.type == C.PUBLISH and pkt.packet_id:
                        _ok, follow = self.session.puback(pkt.packet_id)
                        if follow:
                            pending.extend(follow)

    def connection_lost(self, reason: str) -> None:
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
            self._keepalive_task = None
        self.gateway.conns.pop(self.conn_id, None)
        self.call_handler(
            "OnSocketClosed",
            pb.SocketClosedRequest(conn=self.conn_id, reason=reason),
        )
        super().connection_lost(reason)

    # ------------------------------------------------- adapter actions
    # (invoked on the event loop via the AdapterServer's marshalling)

    def adapter_authenticate(self, ci: "pb.ClientInfo",
                             password: str) -> Tuple[int, str]:
        clientid = ci.clientid
        if not clientid:
            return REQUIRED_PARAMS_MISSED, "clientid required"
        client = ClientInfo(
            clientid=clientid,
            username=ci.username or None,
            password=password.encode() or None,
            peerhost=self.peer,
            mountpoint=ci.mountpoint or None,
        )
        if self.broker.banned.is_banned(
            clientid=clientid, username=client.username,
            peerhost=self.peer.rsplit(":", 1)[0],
        ):
            return PERMISSION_DENY, "banned"
        ok, client = self.broker.access.authenticate(client)
        if not ok:
            return PERMISSION_DENY, "authentication failed"
        client.password = None
        self.client = client
        self.open_session(clientid, clean_start=True)
        return SUCCESS, ""

    def adapter_subscribe(self, topic: str, qos: int) -> Tuple[int, str]:
        if self.session is None:
            return CONN_PROCESS_NOT_ALIVE, "not authenticated"
        if not self.broker.access.authorize(
            self.client, ACT_SUBSCRIBE, topic
        ):
            return PERMISSION_DENY, "subscribe not authorized"
        opts = SubOpts(qos=min(max(qos, 0), 2))
        is_new = self.session.subscribe(topic, opts)
        self.broker.subscribe(self.clientid, topic, opts, is_new_sub=is_new)
        return SUCCESS, ""

    def adapter_unsubscribe(self, topic: str) -> Tuple[int, str]:
        if self.session is None:
            return CONN_PROCESS_NOT_ALIVE, "not authenticated"
        self.session.unsubscribe(topic)
        self.broker.unsubscribe(self.clientid, topic)
        return SUCCESS, ""

    def adapter_publish(self, topic: str, qos: int,
                        payload: bytes) -> Tuple[int, str]:
        if self.session is None:
            return CONN_PROCESS_NOT_ALIVE, "not authenticated"
        if not self.broker.access.authorize(self.client, ACT_PUBLISH, topic):
            return PERMISSION_DENY, "publish not authorized"
        self.broker_publish(Message(
            topic=topic, payload=payload, qos=min(max(qos, 0), 2),
            from_client=self.clientid,
            from_username=self.client.username if self.client else None,
        ))
        return SUCCESS, ""

    def adapter_start_timer(self, interval_s: int) -> Tuple[int, str]:
        self.keepalive_s = float(interval_s)
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        if interval_s > 0:
            self._keepalive_task = asyncio.get_running_loop().create_task(
                self._keepalive_watch()
            )
        return SUCCESS, ""

    async def _keepalive_watch(self) -> None:
        while True:
            await asyncio.sleep(self.keepalive_s / 2)
            if time.monotonic() - self.last_rx > self.keepalive_s * 1.5:
                self.call_handler(
                    "OnTimerTimeout",
                    pb.TimerTimeoutRequest(conn=self.conn_id,
                                           type=pb.KEEPALIVE),
                )
                self.close("keepalive_timeout")
                return


class ExprotoGateway(Gateway):
    """TCP side + both gRPC halves of the exproto contract."""

    name = "exproto"
    frame_class = _RawFrame
    channel_class = ExprotoChannel

    def __init__(
        self,
        broker,
        bind: str = "0.0.0.0",
        port: int = 0,
        handler_address: str = "127.0.0.1:9100",
        adapter_bind: str = "127.0.0.1:0",
    ) -> None:
        super().__init__(broker, bind, port)
        import grpc

        self.node = broker.config.node_name
        self.conns: Dict[str, ExprotoChannel] = {}
        self._conn_seq = itertools.count(1)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # future-based stubs to the user's handler service
        self._grpc_channel = grpc.insecure_channel(handler_address)
        self._stubs = {
            name: self._grpc_channel.unary_unary(
                f"/{HANDLER_SERVICE}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=pb.EmptySuccess.FromString,
            )
            for name, req in (
                ("OnSocketCreated", pb.SocketCreatedRequest),
                ("OnSocketClosed", pb.SocketClosedRequest),
                ("OnReceivedBytes", pb.ReceivedBytesRequest),
                ("OnTimerTimeout", pb.TimerTimeoutRequest),
                ("OnReceivedMessages", pb.ReceivedMessagesRequest),
            )
        }
        self._adapter = _AdapterServer(self, adapter_bind)

    @property
    def adapter_port(self) -> int:
        return self._adapter.port

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._adapter.start()
        await super().start()

    async def stop(self) -> None:
        await super().stop()
        self._adapter.stop()
        self._grpc_channel.close()

    def call_handler(self, method: str, request, on_done=None) -> None:
        """Unary call to the handler service (the future keeps the loop
        unblocked; failures are logged — the reference's handler pool
        behaves the same on a dead service).  ``on_done`` always fires
        (channels chain their per-connection call order on it)."""
        try:
            fut = self._stubs[method].future(request, timeout=10.0)
        except Exception:
            log.exception("exproto handler call %s failed to start", method)
            if on_done is not None:
                on_done(None)
            return

        def done(f):
            exc = f.exception()
            if exc is not None:
                log.warning("exproto handler %s failed: %s", method, exc)
                self.broker.metrics.inc("gateway.exproto.handler_error")
            if on_done is not None:
                on_done(f)

        fut.add_done_callback(done)


class _AdapterServer:
    """Serves ConnectionAdapter for the external handler service."""

    def __init__(self, gateway: ExprotoGateway, bind: str) -> None:
        import grpc

        self.gateway = gateway
        self._grpc = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._grpc.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                ADAPTER_SERVICE, self._handlers()
            ),
        ))
        self.port = self._grpc.add_insecure_port(bind)

    def start(self) -> None:
        self._grpc.start()
        log.info("exproto ConnectionAdapter serving on port %d", self.port)

    def stop(self, grace: float = 0.5) -> None:
        self._grpc.stop(grace).wait()

    # ------------------------------------------------------- plumbing

    def _on_loop(self, fn) -> Tuple[int, str]:
        """Run ``fn`` on the gateway's event loop and wait for its
        (code, message) result — adapter RPCs arrive on gRPC worker
        threads, but all broker/channel state lives on the loop."""
        loop = self.gateway._loop
        if loop is None or loop.is_closed():
            return CONN_PROCESS_NOT_ALIVE, "gateway not running"
        done = threading.Event()
        box: List = [UNKNOWN, "internal"]

        def run():
            try:
                box[0], box[1] = fn()
            except Exception as exc:  # pragma: no cover - defensive
                log.exception("exproto adapter action failed")
                box[0], box[1] = UNKNOWN, str(exc)
            finally:
                done.set()

        loop.call_soon_threadsafe(run)
        if not done.wait(10.0):
            return UNKNOWN, "loop timeout"
        return box[0], box[1]

    def _conn(self, conn_id: str) -> Optional[ExprotoChannel]:
        return self.gateway.conns.get(conn_id)

    def _handlers(self):
        import grpc

        def unary(fn, req_cls):
            def call(request, context):
                try:
                    code, msg = fn(request)
                except Exception:
                    log.exception("exproto adapter %s failed", fn.__name__)
                    code, msg = UNKNOWN, "internal error"
                return pb.CodeResponse(code=code, message=msg)

            return grpc.unary_unary_rpc_method_handler(
                call,
                request_deserializer=req_cls.FromString,
                response_serializer=pb.CodeResponse.SerializeToString,
            )

        def with_conn(action):
            def fn(request):
                def on_loop():
                    chan = self._conn(request.conn)
                    if chan is None:
                        return CONN_PROCESS_NOT_ALIVE, "no such connection"
                    return action(chan, request)

                return self._on_loop(on_loop)

            return fn

        return {
            "Send": unary(
                with_conn(lambda ch, r: (ch.write(bytes(r.bytes)),
                                         (SUCCESS, ""))[1]),
                pb.SendBytesRequest,
            ),
            "Close": unary(
                with_conn(lambda ch, r: (ch.close("adapter_close"),
                                         (SUCCESS, ""))[1]),
                pb.CloseSocketRequest,
            ),
            "Authenticate": unary(
                with_conn(lambda ch, r: ch.adapter_authenticate(
                    r.clientinfo, r.password)),
                pb.AuthenticateRequest,
            ),
            "StartTimer": unary(
                with_conn(lambda ch, r: ch.adapter_start_timer(r.interval)),
                pb.TimerRequest,
            ),
            "Publish": unary(
                with_conn(lambda ch, r: ch.adapter_publish(
                    r.topic, r.qos, bytes(r.payload))),
                pb.PublishRequest,
            ),
            "Subscribe": unary(
                with_conn(lambda ch, r: ch.adapter_subscribe(
                    r.topic, r.qos)),
                pb.SubscribeRequest,
            ),
            "Unsubscribe": unary(
                with_conn(lambda ch, r: ch.adapter_unsubscribe(r.topic)),
                pb.UnsubscribeRequest,
            ),
            "RawPublish": unary(self._raw_publish, pb.RawPublishRequest),
        }

    def _raw_publish(self, request) -> Tuple[int, str]:
        def on_loop():
            self.gateway.broker.publish(Message(
                topic=request.topic,
                payload=bytes(request.payload),
                qos=min(max(request.qos, 0), 2),
                from_client="exproto",
            ))
            return SUCCESS, ""

        return self._on_loop(on_loop)
