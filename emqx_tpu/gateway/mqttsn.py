"""MQTT-SN (v1.2) gateway over UDP.

Capability match for the reference's MQTT-SN gateway
(/root/reference/apps/emqx_gateway_mqttsn/src/emqx_mqttsn_frame.erl
wire codec, emqx_mqttsn_channel.erl session bridge): topic-id
registration both directions, QoS 0/1/2 publish, QoS -1
publish-without-connection on predefined/short topics, wildcard
subscribe, sleeping clients (DISCONNECT with duration buffers
deliveries until PINGREQ wake), SEARCHGW/GWINFO discovery.

The channel adapts datagrams onto the same broker core the MQTT
listeners use: publishes ride the shared micro-batcher, deliveries
arrive as MQTT Publish packets from the session and are re-framed as
SN PUBLISH (with an on-demand REGISTER round-trip when the client
doesn't know the topic id yet)."""

from __future__ import annotations

import asyncio
import logging
import secrets
import struct
import time
from typing import Dict, List, Optional, Tuple

from ..access import PUBLISH, SUBSCRIBE, ClientInfo
from ..aio import cancel_and_wait
from ..codec import mqtt as C
from ..message import Message
from ..broker.session import SubOpts
from . import GatewayChannel, GatewayFrame, UdpGateway

log = logging.getLogger("emqx_tpu.gateway.mqttsn")

# message types (MQTT-SN spec v1.2 §5.2.1)
ADVERTISE = 0x00
SEARCHGW = 0x01
GWINFO = 0x02
CONNECT = 0x04
CONNACK = 0x05
WILLTOPICREQ = 0x06
WILLTOPIC = 0x07
WILLMSGREQ = 0x08
WILLMSG = 0x09
REGISTER = 0x0A
REGACK = 0x0B
PUBLISH = 0x0C
PUBACK = 0x0D
PUBCOMP = 0x0E
PUBREC = 0x0F
PUBREL = 0x10
SUBSCRIBE_SN = 0x12
SUBACK = 0x13
UNSUBSCRIBE = 0x14
UNSUBACK = 0x15
PINGREQ = 0x16
PINGRESP = 0x17
DISCONNECT = 0x18

# flag bits (§5.3.4)
FLAG_DUP = 0x80
FLAG_QOS = 0x60
FLAG_RETAIN = 0x10
FLAG_WILL = 0x08
FLAG_CLEAN = 0x04
FLAG_TOPIC_TYPE = 0x03

TOPIC_NORMAL = 0x00  # registered topic id
TOPIC_PREDEF = 0x01
TOPIC_SHORT = 0x02  # 2-char topic name carried in the id field

RC_ACCEPTED = 0x00
RC_CONGESTION = 0x01
RC_INVALID_TOPIC = 0x02
RC_NOT_SUPPORTED = 0x03

GATEWAY_ID = 1


def _qos_bits(flags: int) -> int:
    """QoS field: 0b11 encodes QoS -1 (publish without connection)."""
    q = (flags & FLAG_QOS) >> 5
    return -1 if q == 3 else q


class SnFrame:
    __slots__ = ("msg_type", "fields")

    def __init__(self, msg_type: int, **fields) -> None:
        self.msg_type = msg_type
        self.fields = fields

    def __getattr__(self, name):
        try:
            return self.fields[name]
        except KeyError:
            raise AttributeError(name) from None

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SnFrame({self.msg_type:#04x}, {self.fields})"


class SnCodec(GatewayFrame):
    """One datagram = one frame (§5.2: length, msgtype, variable part)."""

    def parse(self, state, data: bytes) -> Tuple[List[SnFrame], object]:
        if len(data) < 2:
            raise ValueError("short datagram")
        if data[0] == 0x01:
            if len(data) < 4:
                raise ValueError("short extended-length datagram")
            length = struct.unpack_from(">H", data, 1)[0]
            off = 3
        else:
            length = data[0]
            off = 1
        if length != len(data):
            raise ValueError(f"length mismatch: {length} != {len(data)}")
        t = data[off]
        body = data[off + 1 :]
        return [self._parse_body(t, body)], state

    def _parse_body(self, t: int, b: bytes) -> SnFrame:
        if t == SEARCHGW:
            return SnFrame(t, radius=b[0] if b else 0)
        if t == GWINFO:
            return SnFrame(t, gw_id=b[0] if b else 0)
        if t == ADVERTISE:
            return SnFrame(t, gw_id=b[0],
                           duration=struct.unpack_from(">H", b, 1)[0])
        if t == CONNACK:
            return SnFrame(t, rc=b[0] if b else 0)
        if t in (WILLTOPICREQ, WILLMSGREQ, PINGRESP):
            return SnFrame(t)
        if t == SUBACK:
            flags = b[0]
            tid, mid = struct.unpack_from(">HH", b, 1)
            return SnFrame(t, flags=flags, topic_id=tid, msg_id=mid,
                           rc=b[5])
        if t == UNSUBACK:
            return SnFrame(t, msg_id=struct.unpack_from(">H", b, 0)[0])
        if t == CONNECT:
            if len(b) < 4:
                raise ValueError("short CONNECT")
            flags, proto_id = b[0], b[1]
            duration = struct.unpack_from(">H", b, 2)[0]
            return SnFrame(
                t, flags=flags, protocol_id=proto_id, duration=duration,
                client_id=b[4:].decode("utf-8", "replace"),
            )
        if t in (WILLTOPIC, WILLMSG):
            if t == WILLTOPIC:
                if not b:  # empty WILLTOPIC clears the will
                    return SnFrame(t, flags=0, topic="")
                return SnFrame(t, flags=b[0],
                               topic=b[1:].decode("utf-8", "replace"))
            return SnFrame(t, data=b)
        if t == REGISTER:
            tid, mid = struct.unpack_from(">HH", b, 0)
            return SnFrame(t, topic_id=tid, msg_id=mid,
                           topic=b[4:].decode("utf-8", "replace"))
        if t == REGACK:
            tid, mid = struct.unpack_from(">HH", b, 0)
            return SnFrame(t, topic_id=tid, msg_id=mid, rc=b[4])
        if t == PUBLISH:
            flags = b[0]
            tid, mid = struct.unpack_from(">HH", b, 1)
            return SnFrame(t, flags=flags, topic_id=tid, msg_id=mid,
                           data=b[5:])
        if t == PUBACK:
            tid, mid = struct.unpack_from(">HH", b, 0)
            return SnFrame(t, topic_id=tid, msg_id=mid, rc=b[4])
        if t in (PUBREC, PUBREL, PUBCOMP):
            return SnFrame(t, msg_id=struct.unpack_from(">H", b, 0)[0])
        if t in (SUBSCRIBE_SN, UNSUBSCRIBE):
            flags = b[0]
            mid = struct.unpack_from(">H", b, 1)[0]
            tt = flags & FLAG_TOPIC_TYPE
            rest = b[3:]
            if tt == TOPIC_NORMAL:  # topic NAME (possibly wildcard)
                return SnFrame(t, flags=flags, msg_id=mid,
                               topic=rest.decode("utf-8", "replace"))
            if tt == TOPIC_SHORT:
                return SnFrame(t, flags=flags, msg_id=mid,
                               topic=rest[:2].decode("utf-8", "replace"))
            return SnFrame(t, flags=flags, msg_id=mid,
                           topic_id=struct.unpack_from(">H", rest, 0)[0])
        if t == PINGREQ:
            return SnFrame(t, client_id=b.decode("utf-8", "replace"))
        if t == DISCONNECT:
            duration = struct.unpack_from(">H", b, 0)[0] if len(b) >= 2 else None
            return SnFrame(t, duration=duration)
        return SnFrame(t, raw=b)

    def serialize(self, frame: SnFrame) -> bytes:
        t = frame.msg_type
        f = frame.fields
        if t == GWINFO:
            body = bytes([f["gw_id"]])
        elif t == ADVERTISE:
            body = bytes([f["gw_id"]]) + struct.pack(">H", f["duration"])
        elif t == SEARCHGW:
            body = bytes([f.get("radius", 0)])
        elif t == CONNECT:
            body = (bytes([f["flags"], f.get("protocol_id", 1)])
                    + struct.pack(">H", f["duration"])
                    + f["client_id"].encode())
        elif t == WILLTOPIC:
            topic = f.get("topic", "")
            body = (bytes([f.get("flags", 0)]) + topic.encode()
                    if topic else b"")
        elif t == WILLMSG:
            body = f["data"]
        elif t in (SUBSCRIBE_SN, UNSUBSCRIBE):
            flags = f.get("flags", 0)
            body = bytes([flags]) + struct.pack(">H", f["msg_id"])
            if "topic" in f:
                body += f["topic"].encode()
            else:
                body += struct.pack(">H", f["topic_id"])
        elif t == PINGREQ:
            body = f.get("client_id", "").encode()
        elif t == CONNACK:
            body = bytes([f["rc"]])
        elif t in (WILLTOPICREQ, WILLMSGREQ):
            body = b""
        elif t == REGISTER:
            body = (struct.pack(">HH", f["topic_id"], f["msg_id"])
                    + f["topic"].encode())
        elif t == REGACK:
            body = struct.pack(">HH", f["topic_id"], f["msg_id"]) + bytes(
                [f["rc"]])
        elif t == PUBLISH:
            body = (bytes([f["flags"]])
                    + struct.pack(">HH", f["topic_id"], f["msg_id"])
                    + f["data"])
        elif t == PUBACK:
            body = struct.pack(">HH", f["topic_id"], f["msg_id"]) + bytes(
                [f["rc"]])
        elif t in (PUBREC, PUBREL, PUBCOMP):
            body = struct.pack(">H", f["msg_id"])
        elif t == SUBACK:
            body = (bytes([f.get("flags", 0)])
                    + struct.pack(">HH", f["topic_id"], f["msg_id"])
                    + bytes([f["rc"]]))
        elif t == UNSUBACK:
            body = struct.pack(">H", f["msg_id"])
        elif t == PINGRESP:
            body = b""
        elif t == DISCONNECT:
            d = f.get("duration")
            body = b"" if d is None else struct.pack(">H", d)
        else:
            body = f.get("raw", b"")
        total = len(body) + 2
        if total + 0 < 256:
            return bytes([total, t]) + body
        return b"\x01" + struct.pack(">H", total + 2) + bytes([t]) + body


class SnChannel(GatewayChannel):
    """Per-peer MQTT-SN state machine (emqx_mqttsn_channel.erl parity:
    register/publish/subscribe flows, sleeping state, will setup)."""

    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.codec: SnCodec = gateway.frame
        self.client: Optional[ClientInfo] = None
        self.connected = False
        self.asleep = False
        # topic registry, both directions (client REGISTER + ours)
        self._id_by_topic: Dict[str, int] = {}
        self._topic_by_id: Dict[int, str] = {}
        self._next_tid = 1
        self._next_mid = 1
        # deliveries parked on an outstanding REGISTER msg_id
        self._awaiting_reg: Dict[int, Tuple[int, List[C.Packet]]] = {}
        self._asleep_buffer: List[C.Packet] = []
        self._awaiting_rel: Dict[int, Message] = {}  # inbound QoS2
        self._pending_connect: Optional[SnFrame] = None
        self._will_topic: Optional[str] = None
        self._will_flags = 0
        self.will_msg: Optional[Message] = None
        # set while sleeping: the UDP reaper honors this instead of the
        # default idle timeout (§6.14 sleep duration)
        self.idle_deadline: Optional[float] = None

    # ------------------------------------------------------------ util

    def _send(self, frame: SnFrame) -> None:
        self.write(self.codec.serialize(frame))

    def _alloc_mid(self) -> int:
        mid = self._next_mid
        self._next_mid = mid % 0xFFFF + 1
        return mid

    def _register_topic(self, topic: str) -> int:
        tid = self._id_by_topic.get(topic)
        if tid is None:
            tid = self._next_tid
            self._next_tid += 1
            self._id_by_topic[topic] = tid
            self._topic_by_id[tid] = topic
        return tid

    def _resolve(self, topic_type: int, topic_id: int) -> Optional[str]:
        if topic_type == TOPIC_NORMAL:
            return self._topic_by_id.get(topic_id)
        if topic_type == TOPIC_PREDEF:
            return self.gateway.predefined.get(topic_id)
        if topic_type == TOPIC_SHORT:
            raw = struct.pack(">H", topic_id)
            try:
                return raw.decode("utf-8")
            except UnicodeDecodeError:
                return None
        return None

    # ------------------------------------------------------ frame pump

    def handle_frame(self, frame: SnFrame) -> None:
        t = frame.msg_type
        if t == SEARCHGW:
            self._send(SnFrame(GWINFO, gw_id=GATEWAY_ID))
            return
        if t == CONNECT:
            self._handle_connect(frame)
            return
        if t == WILLTOPIC:
            self._will_topic = frame.topic or None
            self._will_flags = frame.flags
            if self._will_topic:
                self._send(SnFrame(WILLMSGREQ))
            else:
                self._finish_connect()
            return
        if t == WILLMSG:
            self._finish_connect(will_msg=frame.data)
            return
        if t == PUBLISH and _qos_bits(frame.flags) == -1:
            # QoS -1: fire-and-forget without a session (§6.8)
            self._publish_qos_neg1(frame)
            return
        if not self.connected:
            return
        if t == REGISTER:
            tid = self._register_topic(frame.topic)
            self._send(SnFrame(REGACK, topic_id=tid, msg_id=frame.msg_id,
                               rc=RC_ACCEPTED))
        elif t == REGACK:
            self._handle_regack(frame)
        elif t == PUBLISH:
            self._handle_publish(frame)
        elif t == PUBACK:
            if self.session is not None:
                _ok, follow = self.session.puback(frame.msg_id)
                if follow:
                    self.deliver(follow)
        elif t == PUBREC:
            if self.session is not None:
                self.session.pubrec(frame.msg_id)
            self._send(SnFrame(PUBREL, msg_id=frame.msg_id))
        elif t == PUBCOMP:
            if self.session is not None:
                _ok, follow = self.session.pubcomp(frame.msg_id)
                if follow:
                    self.deliver(follow)
        elif t == PUBREL:
            msg = self._awaiting_rel.pop(frame.msg_id, None)
            if msg is not None:
                self.broker_publish(msg)
            self._send(SnFrame(PUBCOMP, msg_id=frame.msg_id))
        elif t == SUBSCRIBE_SN:
            self._handle_subscribe(frame)
        elif t == UNSUBSCRIBE:
            self._handle_unsubscribe(frame)
        elif t == PINGREQ:
            if self.asleep and frame.client_id:
                self._wake()
            self._send(SnFrame(PINGRESP))
        elif t == DISCONNECT:
            self._handle_disconnect(frame)

    # ------------------------------------------------------- lifecycle

    def _handle_connect(self, frame: SnFrame) -> None:
        self._pending_connect = frame
        # a fresh CONNECT must not inherit a previous session's will
        # (MQTT-SN §6.3: the Will flag alone governs will setup)
        self._will_topic = None
        self._will_flags = 0
        self.will_msg = None
        if frame.flags & FLAG_WILL:
            self._send(SnFrame(WILLTOPICREQ))
        else:
            self._finish_connect()

    def _finish_connect(self, will_msg: bytes = b"") -> None:
        frame = self._pending_connect
        if frame is None:
            return
        clientid = frame.client_id or "sn-" + secrets.token_hex(4)
        client = ClientInfo(clientid=clientid, peerhost=self.peer)
        if self.broker.banned.is_banned(
            clientid=clientid, peerhost=self.peer.rsplit(":", 1)[0]
        ):
            self._reject_connect()
            return
        ok, client = self.broker.access.authenticate(client)
        if not ok:
            self._reject_connect()
            return
        self.client = client
        clean = bool(frame.flags & FLAG_CLEAN)
        self.open_session(clientid, clean_start=clean)
        if self._will_topic:
            qos = (self._will_flags & FLAG_QOS) >> 5
            self.will_msg = Message(
                topic=self._will_topic, payload=will_msg,
                qos=min(qos, 2),
                retain=bool(self._will_flags & FLAG_RETAIN),
                from_client=clientid,
            )
        self.connected = True
        self.asleep = False
        self._pending_connect = None
        self._send(SnFrame(CONNACK, rc=RC_ACCEPTED))

    def _reject_connect(self) -> None:
        """Clear the half-open CONNECT state so a stray WILLMSG cannot
        re-enter _finish_connect and bypass the ban/auth verdict."""
        self._pending_connect = None
        self._will_topic = None
        self._will_flags = 0
        self._send(SnFrame(CONNACK, rc=RC_NOT_SUPPORTED))

    def _handle_disconnect(self, frame: SnFrame) -> None:
        if frame.duration and self.session is not None:
            # sleeping client (§6.14): session stays; buffer deliveries
            # until PINGREQ wake or the announced duration lapses
            self.asleep = True
            self.idle_deadline = time.monotonic() + frame.duration * 1.5
            self._send(SnFrame(DISCONNECT))
            return
        self._send(SnFrame(DISCONNECT))
        self.connected = False
        self.will_msg = None  # graceful disconnect cancels the will
        self.close("client_disconnect")

    def _wake(self) -> None:
        self.asleep = False
        self.idle_deadline = None
        buffered, self._asleep_buffer = self._asleep_buffer, []
        if buffered:
            self.deliver(buffered)

    def connection_lost(self, reason: str) -> None:
        if (self.connected and self.will_msg is not None
                and reason not in ("client_disconnect", "takeover")):
            will, self.will_msg = self.will_msg, None
            self.broker.publish(will)
        super().connection_lost(reason)

    # -------------------------------------------------------- publish

    def _publish_qos_neg1(self, frame: SnFrame) -> None:
        tt = frame.flags & FLAG_TOPIC_TYPE
        if tt == TOPIC_NORMAL:
            return  # normal ids need a connection to be registered
        topic = self._resolve(tt, frame.topic_id)
        if topic is None:
            return
        # connectionless != unpoliced: the anonymous publisher still
        # goes through ban, authentication, and ACL like every other
        # publish path
        host = self.peer.rsplit(":", 1)[0]
        if self.broker.banned.is_banned(clientid="sn-anonymous",
                                        peerhost=host):
            return
        client = self.client
        if client is None:
            ok, client = self.broker.access.authenticate(
                ClientInfo(clientid="sn-anonymous", peerhost=self.peer)
            )
            if not ok:
                return
        if not self.broker.access.authorize(client, PUBLISH, topic):
            return
        self.broker_publish(Message(
            topic=topic, payload=frame.data, qos=0,
            retain=bool(frame.flags & FLAG_RETAIN),
            from_client="sn-anonymous",
        ))

    def _handle_publish(self, frame: SnFrame) -> None:
        tt = frame.flags & FLAG_TOPIC_TYPE
        topic = self._resolve(tt, frame.topic_id)
        qos = max(_qos_bits(frame.flags), 0)
        if topic is None:
            if qos >= 1:
                self._send(SnFrame(PUBACK, topic_id=frame.topic_id,
                                   msg_id=frame.msg_id,
                                   rc=RC_INVALID_TOPIC))
            return
        if not self.broker.access.authorize(self.client, PUBLISH, topic):
            if qos >= 1:
                self._send(SnFrame(PUBACK, topic_id=frame.topic_id,
                                   msg_id=frame.msg_id,
                                   rc=RC_NOT_SUPPORTED))
            return
        msg = Message(
            topic=topic, payload=frame.data, qos=min(qos, 2),
            retain=bool(frame.flags & FLAG_RETAIN),
            from_client=self.clientid,
            from_username=self.client.username if self.client else None,
        )
        if qos == 2:
            self._awaiting_rel[frame.msg_id] = msg
            self._send(SnFrame(PUBREC, msg_id=frame.msg_id))
            return
        self.broker_publish(msg)
        if qos == 1:
            self._send(SnFrame(PUBACK, topic_id=frame.topic_id,
                               msg_id=frame.msg_id, rc=RC_ACCEPTED))

    # ------------------------------------------------------ subscribe

    def _handle_subscribe(self, frame: SnFrame) -> None:
        qos = max(_qos_bits(frame.flags), 0)
        tt = frame.flags & FLAG_TOPIC_TYPE
        if "topic" in frame.fields:
            flt = frame.topic
        else:
            flt = self._resolve(tt, frame.topic_id)
        if not flt:
            self._send(SnFrame(SUBACK, topic_id=0, msg_id=frame.msg_id,
                               rc=RC_INVALID_TOPIC))
            return
        if not self.broker.access.authorize(self.client, SUBSCRIBE, flt):
            self._send(SnFrame(SUBACK, topic_id=0, msg_id=frame.msg_id,
                               rc=RC_NOT_SUPPORTED))
            return
        opts = SubOpts(qos=min(qos, 2))
        is_new = self.session.subscribe(flt, opts)
        self.broker.subscribe(self.clientid, flt, opts, is_new_sub=is_new)
        # a concrete topic gets an id the client can PUBLISH to;
        # wildcard filters get 0 (ids arrive via REGISTER on delivery)
        tid = 0
        if "+" not in flt and "#" not in flt:
            tid = self._register_topic(flt)
        self._send(SnFrame(SUBACK,
                           flags=(min(qos, 2) << 5), topic_id=tid,
                           msg_id=frame.msg_id, rc=RC_ACCEPTED))

    def _handle_unsubscribe(self, frame: SnFrame) -> None:
        tt = frame.flags & FLAG_TOPIC_TYPE
        flt = frame.fields.get("topic") or self._resolve(
            tt, frame.fields.get("topic_id", 0))
        if flt and self.session is not None:
            self.session.unsubscribe(flt)
            self.broker.unsubscribe(self.clientid, flt)
        self._send(SnFrame(UNSUBACK, msg_id=frame.msg_id))

    # ----------------------------------------------------- deliveries

    def _handle_regack(self, frame: SnFrame) -> None:
        parked = self._awaiting_reg.pop(frame.msg_id, None)
        if parked is None:
            return
        tid, packets = parked
        if frame.rc == RC_ACCEPTED:
            self.deliver(packets)
        # rejected: drop — client refused the topic registration

    def deliver(self, packets) -> None:
        if self.asleep:
            # PUBREL must survive sleep too, or an in-flight outbound
            # QoS 2 handshake never completes after wake
            self._asleep_buffer.extend(
                p for p in packets if p.type in (C.PUBLISH, C.PUBREL))
            return
        for pkt in packets:
            if pkt.type == C.PUBREL:
                self._send(SnFrame(PUBREL, msg_id=pkt.packet_id))
                continue
            if pkt.type != C.PUBLISH:
                continue
            topic = pkt.topic
            tt = TOPIC_NORMAL
            enc = topic.encode()
            if len(enc) == 2 and "+" not in topic and "#" not in topic:
                tt = TOPIC_SHORT
                tid = struct.unpack(">H", enc)[0]
            else:
                tid = self._id_by_topic.get(topic)
                if tid is None:
                    # client doesn't know this topic: REGISTER first,
                    # park the delivery until REGACK (§6.10)
                    tid = self._register_topic(topic)
                    mid = self._alloc_mid()
                    self._awaiting_reg[mid] = (tid, [pkt])
                    self._send(SnFrame(REGISTER, topic_id=tid, msg_id=mid,
                                       topic=topic))
                    continue
            flags = (min(pkt.qos, 2) << 5) | tt
            if pkt.retain:
                flags |= FLAG_RETAIN
            if getattr(pkt, "dup", False):
                flags |= FLAG_DUP
            self._send(SnFrame(
                PUBLISH, flags=flags, topic_id=tid,
                msg_id=pkt.packet_id or 0, data=pkt.payload))


class MqttSnGateway(UdpGateway):
    name = "mqttsn"
    frame_class = SnCodec
    channel_class = SnChannel

    def __init__(self, broker, bind: str = "0.0.0.0", port: int = 0,
                 predefined: Optional[Dict[int, str]] = None,
                 advertise_interval: float = 0.0,
                 broadcast_addr: str = "255.255.255.255",
                 advertise_port: Optional[int] = None) -> None:
        super().__init__(broker, bind, port)
        # predefined topic ids (gateway.mqttsn.predefined config table)
        self.predefined: Dict[int, str] = dict(predefined or {})
        # gateway ADVERTISE broadcast (spec §6.1 / the reference's
        # mqttsn broadcast option): clients on the segment discover
        # the gateway passively; 0 disables (SEARCHGW still answered).
        # advertise_port defaults to the gateway's own port (clients
        # listen where they'd talk).
        self.advertise_interval = float(advertise_interval)
        self.broadcast_addr = broadcast_addr
        self.advertise_port = advertise_port
        self._advertiser: Optional[asyncio.Task] = None

    async def start(self) -> None:
        await super().start()
        if self.advertise_interval > 0:
            import socket as _socket

            sock = self._transport.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_BROADCAST, 1
                )
            self._advertiser = asyncio.get_running_loop().create_task(
                self._advertise_loop()
            )

    async def stop(self) -> None:
        if self._advertiser is not None:
            await cancel_and_wait(self._advertiser)
            self._advertiser = None
        await super().stop()

    async def _advertise_loop(self) -> None:
        # duration tells clients when to expect the NEXT advertise
        # (spec: T_ADV); rounded UP so a sub-second interval never
        # advertises 0 (= "already stale"), capped to the u16 field
        import math

        frame = SnFrame(
            ADVERTISE,
            gw_id=GATEWAY_ID,
            duration=min(
                max(1, math.ceil(self.advertise_interval)), 0xFFFF
            ),
        )
        data = self.frame.serialize(frame)
        target = (self.broadcast_addr, self.advertise_port or self.port)
        while True:
            try:
                self._transport.sendto(data, target)
            except OSError:
                log.debug("mqttsn advertise send failed", exc_info=True)
            await asyncio.sleep(self.advertise_interval)
