"""JT/T 808 gateway: vehicle terminals bridged to MQTT.

The `emqx_gateway_jt808` role (/root/reference/apps/emqx_gateway_jt808/
src/emqx_jt808_frame.erl framing, emqx_jt808_channel.erl message
handling); the codec is written from the public JT/T 808-2013
specification:

    frame   = 0x7e escaped(header body checksum) 0x7e
    escape  : 0x7e -> 0x7d 0x02,  0x7d -> 0x7d 0x01
    header  = msg_id(2) attrs(2) phone BCD(6) serial(2)
              [package info(4) when attrs bit 13]
    check   = XOR over header+body

Terminal messages handled natively: 0x0100 register (answered 0x8100
with a minted auth code), 0x0102 authenticate, 0x0002 heartbeat and
0x0003 unregister (0x8001 general ack), 0x0200 location report
(decoded: alarm/status bits, lat/lon x1e-6, altitude, speed x0.1km/h,
direction, BCD time).  Once the channel is AUTHENTICATED, terminal
frames also publish upstream as
JSON to ``{mountpoint}{phone}/up``; the platform side publishes JSON
to ``{mountpoint}{phone}/dn`` — either ``{"msg_id": ..., "body_hex":
...}`` raw passthrough or ``{"text": ...}`` (0x8300 text message) —
which this gateway frames back to the terminal.

Explicit cuts: subpackaged (multi-frame) messages and RSA encryption
(attrs bits) are rejected, 2019-edition version markers are not
parsed, and the auth-code store is in-memory per gateway."""

from __future__ import annotations

import json
import secrets
import struct
from typing import Dict, List, Optional, Tuple

from ..access import PUBLISH, SUBSCRIBE, ClientInfo
from ..broker.session import SubOpts
from ..message import Message
from . import Gateway, GatewayChannel, GatewayFrame

FLAG = 0x7E
MAX_FRAME = 4096

# terminal -> platform
MSG_HEARTBEAT = 0x0002
MSG_UNREGISTER = 0x0003
MSG_REGISTER = 0x0100
MSG_AUTH = 0x0102
MSG_LOCATION = 0x0200
# platform -> terminal
MSG_GENERAL_ACK = 0x8001
MSG_REGISTER_ACK = 0x8100
MSG_TEXT = 0x8300


def _escape(data: bytes) -> bytes:
    return data.replace(b"\x7d", b"\x7d\x01").replace(
        b"\x7e", b"\x7d\x02"
    )


def _unescape(data: bytes) -> bytes:
    return data.replace(b"\x7d\x02", b"\x7e").replace(
        b"\x7d\x01", b"\x7d"
    )


def _xor(data: bytes) -> int:
    c = 0
    for b in data:
        c ^= b
    return c


def _bcd(data: bytes) -> str:
    return data.hex()


def _to_bcd(digits: str, width: int) -> bytes:
    digits = digits.rjust(width * 2, "0")[-width * 2:]
    return bytes.fromhex(digits)


class Jt808Message:
    __slots__ = ("msg_id", "phone", "serial", "body")

    def __init__(self, msg_id: int, phone: str, serial: int,
                 body: bytes = b"") -> None:
        self.msg_id = msg_id
        self.phone = phone
        self.serial = serial
        self.body = body


class Jt808Codec(GatewayFrame):
    def initial_state(self) -> bytes:
        return b""

    def parse(
        self, state: bytes, data: bytes
    ) -> Tuple[List[Jt808Message], bytes]:
        buf = state + data
        if len(buf) > MAX_FRAME * 4:
            raise ValueError("jt808: buffer overflow")
        out: List[Jt808Message] = []
        while True:
            start = buf.find(bytes([FLAG]))
            if start < 0:
                return out, b""
            end = buf.find(bytes([FLAG]), start + 1)
            if end < 0:
                return out, buf[start:]
            raw = buf[start + 1:end]
            buf = buf[end + 1:]
            if not raw:
                continue  # back-to-back flags (end+start of frames)
            frame = _unescape(raw)
            if len(frame) < 13:
                raise ValueError("jt808: short frame")
            if _xor(frame[:-1]) != frame[-1]:
                raise ValueError("jt808: checksum mismatch")
            msg_id, attrs = struct.unpack_from(">HH", frame, 0)
            if attrs & 0x2000:
                raise ValueError("jt808: subpackage unsupported")
            if attrs & 0x1C00:
                raise ValueError("jt808: encryption unsupported")
            body_len = attrs & 0x03FF
            phone = _bcd(frame[4:10])
            (serial,) = struct.unpack_from(">H", frame, 10)
            body = frame[12:12 + body_len]
            if len(body) != body_len:
                raise ValueError("jt808: body length mismatch")
            out.append(Jt808Message(msg_id, phone, serial, body))

    def serialize(self, m: Jt808Message) -> bytes:
        header = (
            struct.pack(">HH", m.msg_id, len(m.body) & 0x03FF)
            + _to_bcd(m.phone, 6)
            + struct.pack(">H", m.serial)
        )
        payload = header + m.body
        payload += bytes([_xor(payload)])
        return bytes([FLAG]) + _escape(payload) + bytes([FLAG])


def decode_location(body: bytes) -> Dict:
    """0x0200 basic position block (extras pass through as hex)."""
    alarm, status, lat, lon = struct.unpack_from(">IIII", body, 0)
    alt, speed, direction = struct.unpack_from(">HHH", body, 16)
    t = _bcd(body[22:28])
    return {
        "alarm": alarm,
        "status": status,
        "lat": lat / 1e6,
        "lon": lon / 1e6,
        "altitude": alt,
        "speed_kmh": speed / 10.0,
        "direction": direction,
        "time": f"20{t[0:2]}-{t[2:4]}-{t[4:6]} "
                f"{t[6:8]}:{t[8:10]}:{t[10:12]}",
        "extras_hex": body[28:].hex(),
    }


class Jt808Channel(GatewayChannel):
    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.phone: Optional[str] = None
        self.client: Optional[ClientInfo] = None
        self.authed = False
        self._serial = 0

    def _next_serial(self) -> int:
        self._serial = (self._serial + 1) & 0xFFFF
        return self._serial

    def _send(self, msg_id: int, body: bytes) -> None:
        self.write(self.gateway.frame.serialize(Jt808Message(
            msg_id, self.phone or "0", self._next_serial(), body
        )))

    def _general_ack(self, m: Jt808Message, result: int = 0) -> None:
        self._send(MSG_GENERAL_ACK,
                   struct.pack(">HHB", m.serial, m.msg_id, result))

    def _uplink(self, kind: str, m: Jt808Message, extra: Dict) -> None:
        if not self.authed or self.client is None:
            # pre-auth frames (register path) must not publish: an
            # attacker-chosen phone would otherwise reach
            # {mountpoint}{phone}/up with no authentication at all
            self.broker.metrics.inc("gateway.jt808.preauth_drop")
            return
        topic = f"{self.gateway.mountpoint}{self.phone}/up"
        if not self.broker.access.authorize(
            self.client, PUBLISH, topic
        ):
            self.broker.metrics.inc("authorization.deny")
            return
        self.broker_publish(Message(
            topic=topic,
            payload=json.dumps({
                "msg_id": m.msg_id, "type": kind,
                "serial": m.serial, **extra,
            }).encode(),
            qos=self.gateway.qos,
            from_client=f"jt808-{self.phone}",
        ))

    # -------------------------------------------------------- frames

    def handle_frame(self, m: Jt808Message) -> None:
        if self.phone is None:
            self.phone = m.phone
        elif m.phone != self.phone:
            # one connection = one terminal: a frame carrying another
            # phone would let a terminal authenticate as ITSELF while
            # publishing telemetry under a VICTIM's uplink topic (the
            # channel identity was pinned by the first frame)
            self.broker.metrics.inc("gateway.jt808.phone_mismatch")
            self._general_ack(m, result=1)
            self.close("phone_mismatch")
            return
        if m.msg_id == MSG_REGISTER:
            self._on_register(m)
            return
        if m.msg_id == MSG_AUTH:
            self._on_auth(m)
            return
        if not self.authed:
            self._general_ack(m, result=1)  # failure: not authed
            return
        if m.msg_id == MSG_LOCATION:
            try:
                loc = decode_location(m.body)
            except struct.error:
                self._general_ack(m, result=2)
                return
            self._uplink("location", m, loc)
            self._general_ack(m)
        elif m.msg_id == MSG_HEARTBEAT:
            self._uplink("heartbeat", m, {})
            self._general_ack(m)
        elif m.msg_id == MSG_UNREGISTER:
            self.gateway.auth_codes.pop(self.phone, None)
            self._general_ack(m)
            self.close("unregistered")
        else:
            self._uplink("raw", m, {"body_hex": m.body.hex()})
            self._general_ack(m)

    def _on_register(self, m: Jt808Message) -> None:
        existing = self.gateway.auth_codes.get(m.phone)
        if existing is not None and not self.authed:
            # 0x8100 result 3: terminal already registered.  A fresh
            # connection re-registering a victim's phone must not mint
            # (and silently overwrite) its auth code — that would let
            # any peer impersonate an enrolled terminal.  The real
            # terminal unregisters (0x0003) before re-enrolling.
            self.broker.metrics.inc("gateway.jt808.reregister_denied")
            self._send(MSG_REGISTER_ACK,
                       struct.pack(">HB", m.serial, 3))
            return
        code = existing or secrets.token_hex(8)
        self.gateway.auth_codes[m.phone] = code
        # 0x8100: serial(2) result(1) auth code
        self._send(MSG_REGISTER_ACK,
                   struct.pack(">HB", m.serial, 0) + code.encode())
        self._uplink("register", m, {"body_hex": m.body.hex()})

    def _on_auth(self, m: Jt808Message) -> None:
        want = self.gateway.auth_codes.get(m.phone)
        given = m.body.decode("utf-8", "replace")
        if want is None or given != want:
            self._general_ack(m, result=1)
            return
        client = ClientInfo(clientid=f"jt808-{m.phone}",
                            peerhost=self.peer)
        ok, client = self.broker.access.authenticate(client)
        dn = f"{self.gateway.mountpoint}{m.phone}/dn"
        if not ok or not self.broker.access.authorize(
            client, SUBSCRIBE, dn
        ):
            self._general_ack(m, result=1)
            return
        self.client = client
        self.authed = True
        self.open_session(client.clientid, clean_start=False)
        opts = SubOpts(qos=self.gateway.qos)
        is_new = self.session.subscribe(dn, opts)
        self.broker.subscribe(client.clientid, dn, opts,
                              is_new_sub=is_new)
        self._general_ack(m, result=0)
        self._uplink("auth", m, {})

    # ------------------------------------------------------ downlink

    def deliver(self, packets) -> None:
        for pkt in packets:
            try:
                cmd = json.loads(pkt.payload)
            except (ValueError, UnicodeDecodeError):
                continue
            if "text" in cmd:
                # 0x8300: flags(1) + GBK text (ascii subset here)
                body = b"\x01" + str(cmd["text"]).encode(
                    "utf-8", "replace"
                )
                self._send(MSG_TEXT, body)
            elif "msg_id" in cmd and "body_hex" in cmd:
                try:
                    self._send(int(cmd["msg_id"]),
                               bytes.fromhex(cmd["body_hex"]))
                except ValueError:
                    continue

    def connection_lost(self, reason: str) -> None:
        super().connection_lost(reason)


class Jt808Gateway(Gateway):
    name = "jt808"
    frame_class = Jt808Codec
    channel_class = Jt808Channel

    def __init__(self, broker, bind: str = "0.0.0.0", port: int = 0,
                 mountpoint: str = "jt808/", qos: int = 1) -> None:
        super().__init__(broker, bind, port)
        self.mountpoint = mountpoint
        self.qos = qos
        self.auth_codes: Dict[str, str] = {}
