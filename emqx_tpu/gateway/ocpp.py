"""OCPP-J gateway: charge points over WebSocket, bridged to MQTT.

The `emqx_gateway_ocpp` role (/root/reference/apps/emqx_gateway_ocpp/
src/emqx_ocpp_frame.erl:70-117 CALL/CALLRESULT/CALLERROR parsing,
emqx_ocpp_schema.erl topic defaults): a charge point connects to
``ws://host:port/ocpp/{cpid}`` with subprotocol ``ocpp1.6`` and speaks
OCPP-J JSON arrays:

    [2, id, action, payload]      CALL
    [3, id, payload]              CALLRESULT
    [4, id, code, desc, details]  CALLERROR

Upstream frames publish as JSON objects (``{"type", "id", "action",
"payload"}``) to ``{mountpoint}cp/{cpid}`` (replies/errors to
``cp/{cpid}/Reply``); the charging-station side publishes downstream
commands to ``{mountpoint}cs/{cpid}``, which this gateway frames back
to the socket.  CALL payloads validate against per-action JSON
schemas for the OCPP 1.6 core profile (the reference's
priv/schemas directory, emqx_ocpp_schemas.erl): a violation answers
CALLERROR ``TypeConstraintViolation``/``ProtocolError`` without
reaching the broker; unknown actions pass through unvalidated
(forward-compatible, as the reference's strict=false mode).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional
from urllib.parse import unquote

from ..access import PUBLISH, SUBSCRIBE, ClientInfo
from ..broker.session import SubOpts
from ..broker.ws import WsError, WsServerStream, frame as ws_frame, \
    server_handshake
from ..message import Message
from . import Gateway, GatewayChannel

log = logging.getLogger("emqx_tpu.gateway.ocpp")

CALL, CALLRESULT, CALLERROR = 2, 3, 4

_OP_TEXT, _OP_CLOSE = 0x1, 0x8

# OCPP 1.6 core-profile action schemas (charge point -> central
# system), transcribed from the spec's JSON schema files
_CP_STATUS = [
    "Available", "Preparing", "Charging", "SuspendedEVSE",
    "SuspendedEV", "Finishing", "Reserved", "Unavailable", "Faulted",
]
_CP_ERROR = [
    "ConnectorLockFailure", "EVCommunicationError", "GroundFailure",
    "HighTemperature", "InternalError", "LocalListConflict",
    "NoError", "OtherError", "OverCurrentFailure", "OverVoltage",
    "PowerMeterFailure", "PowerSwitchFailure", "ReaderFailure",
    "ResetFailure", "UnderVoltage", "WeakSignal",
]
ACTION_SCHEMAS = {
    "BootNotification": {
        "type": "object",
        "required": ["chargePointVendor", "chargePointModel"],
        "properties": {
            "chargePointVendor": {"type": "string", "maxLength": 20},
            "chargePointModel": {"type": "string", "maxLength": 20},
            "chargePointSerialNumber": {"type": "string",
                                        "maxLength": 25},
            "chargeBoxSerialNumber": {"type": "string",
                                      "maxLength": 25},
            "firmwareVersion": {"type": "string", "maxLength": 50},
            "iccid": {"type": "string", "maxLength": 20},
            "imsi": {"type": "string", "maxLength": 20},
            "meterType": {"type": "string", "maxLength": 25},
            "meterSerialNumber": {"type": "string", "maxLength": 25},
        },
        "additionalProperties": False,
    },
    "Heartbeat": {
        "type": "object", "additionalProperties": False,
    },
    "Authorize": {
        "type": "object",
        "required": ["idTag"],
        "properties": {"idTag": {"type": "string", "maxLength": 20}},
        "additionalProperties": False,
    },
    "StatusNotification": {
        "type": "object",
        "required": ["connectorId", "errorCode", "status"],
        "properties": {
            "connectorId": {"type": "integer", "minimum": 0},
            "errorCode": {"enum": _CP_ERROR},
            "status": {"enum": _CP_STATUS},
            "info": {"type": "string", "maxLength": 50},
            "timestamp": {"type": "string"},
            "vendorId": {"type": "string", "maxLength": 255},
            "vendorErrorCode": {"type": "string", "maxLength": 50},
        },
        "additionalProperties": False,
    },
    "StartTransaction": {
        "type": "object",
        "required": ["connectorId", "idTag", "meterStart",
                     "timestamp"],
        "properties": {
            "connectorId": {"type": "integer", "minimum": 1},
            "idTag": {"type": "string", "maxLength": 20},
            "meterStart": {"type": "integer"},
            "reservationId": {"type": "integer"},
            "timestamp": {"type": "string"},
        },
        "additionalProperties": False,
    },
    "StopTransaction": {
        "type": "object",
        "required": ["meterStop", "timestamp", "transactionId"],
        "properties": {
            "idTag": {"type": "string", "maxLength": 20},
            "meterStop": {"type": "integer"},
            "timestamp": {"type": "string"},
            "transactionId": {"type": "integer"},
            "reason": {"enum": [
                "EmergencyStop", "EVDisconnected", "HardReset",
                "Local", "Other", "PowerLoss", "Reboot", "Remote",
                "SoftReset", "UnlockCommand", "DeAuthorized",
            ]},
            "transactionData": {"type": "array"},
        },
        "additionalProperties": False,
    },
    "MeterValues": {
        "type": "object",
        "required": ["connectorId", "meterValue"],
        "properties": {
            "connectorId": {"type": "integer", "minimum": 0},
            "transactionId": {"type": "integer"},
            "meterValue": {
                "type": "array",
                "minItems": 1,
                "items": {
                    "type": "object",
                    "required": ["timestamp", "sampledValue"],
                },
            },
        },
        "additionalProperties": False,
    },
}

_validators: dict = {}


def _validate_call(action: str, payload) -> Optional[str]:
    """None = valid (or unknown action); else the violation text."""
    schema = ACTION_SCHEMAS.get(action)
    if schema is None:
        return None
    v = _validators.get(action)
    if v is None:
        import jsonschema

        v = _validators[action] = jsonschema.Draft202012Validator(
            schema
        )
    err = next(iter(v.iter_errors(payload)), None)
    return None if err is None else err.message


def _cpid_from_path(path: str) -> Optional[str]:
    """Charge-point id = last path segment, minus any query string,
    url-decoded LAST — a cpid must not smuggle topic syntax
    (``+``/``#``/``/``, e.g. ``/ocpp/%23``) into the subscription
    filter, where the default-allow ACL would hand it every other
    charge point's downstream commands."""
    segment = path.split("?", 1)[0].rstrip("/").rsplit("/", 1)[-1]
    cpid = unquote(segment)
    if not cpid or any(
        c in "+#/" or ord(c) < 0x20 for c in cpid
    ):
        return None
    return cpid


class OcppChannel(GatewayChannel):
    """One charge point: WS frames in, MQTT topics out and back."""

    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.cpid: Optional[str] = None

    # -------------------------------------------------------- uplink

    def attach(self, cpid: str) -> bool:
        """Authenticate + open the MQTT session and subscribe the
        downstream topic; False rejects the socket."""
        gw = self.gateway
        client = ClientInfo(clientid=cpid, peerhost=self.peer)
        if self.broker.banned.is_banned(
            clientid=cpid, peerhost=self.peer.rsplit(":", 1)[0]
        ):
            return False
        ok, client = self.broker.access.authenticate(client)
        if not ok:
            return False
        dn = f"{gw.mountpoint}cs/{cpid}"
        if not self.broker.access.authorize(client, SUBSCRIBE, dn):
            return False
        self.client = client
        self.cpid = cpid
        self.open_session(cpid, clean_start=False)
        opts = SubOpts(qos=gw.qos)
        is_new = self.session.subscribe(dn, opts)
        self.broker.subscribe(cpid, dn, opts, is_new_sub=is_new)
        return True

    def handle_frame(self, text: bytes) -> None:
        """One OCPP-J array -> one upstream publish."""
        try:
            arr = json.loads(text)
            mtype = arr[0]
            if mtype == CALL:
                _, mid, action, payload = arr
                violation = _validate_call(action, payload)
                if violation is not None:
                    # spec: answer CALLERROR, never forward the frame
                    self.broker.metrics.inc("gateway.ocpp.schema_error")
                    self.write(ws_frame(_OP_TEXT, json.dumps([
                        CALLERROR, mid, "TypeConstraintViolation",
                        violation[:255], {"action": action},
                    ]).encode()))
                    return
                body = {"type": CALL, "id": mid, "action": action,
                        "payload": payload}
                topic = f"{self.gateway.mountpoint}cp/{self.cpid}"
            elif mtype == CALLRESULT:
                _, mid, payload = arr
                body = {"type": CALLRESULT, "id": mid,
                        "payload": payload}
                topic = (f"{self.gateway.mountpoint}cp/"
                         f"{self.cpid}/Reply")
            elif mtype == CALLERROR:
                _, mid, code, desc, details = arr
                body = {"type": CALLERROR, "id": mid,
                        "error_code": code, "error_desc": desc,
                        "error_details": details}
                topic = (f"{self.gateway.mountpoint}cp/"
                         f"{self.cpid}/Reply")
            else:
                raise ValueError(f"unknown MessageTypeId {mtype}")
        except (ValueError, IndexError, KeyError, TypeError) as exc:
            log.debug("ocpp bad frame from %s: %s", self.cpid, exc)
            self.write(ws_frame(_OP_TEXT, json.dumps([
                CALLERROR, "", "ProtocolError", str(exc), {},
            ]).encode()))
            return
        if not self.broker.access.authorize(self.client, PUBLISH, topic):
            self.broker.metrics.inc("authorization.deny")
            return
        self.broker_publish(Message(
            topic=topic, payload=json.dumps(body).encode(),
            qos=self.gateway.qos, from_client=self.cpid,
        ))

    # ------------------------------------------------------ downlink

    def deliver(self, packets) -> None:
        pending = list(packets)
        while pending:
            pkt = pending.pop(0)
            try:
                body = json.loads(pkt.payload)
                mtype = body.get("type", CALL)
                if mtype == CALL:
                    arr = [CALL, body["id"], body["action"],
                           body.get("payload", {})]
                elif mtype == CALLRESULT:
                    arr = [CALLRESULT, body["id"],
                           body.get("payload", {})]
                else:
                    arr = [CALLERROR, body["id"],
                           body.get("error_code", "GenericError"),
                           body.get("error_desc", ""),
                           body.get("error_details", {})]
                self.write(ws_frame(
                    _OP_TEXT, json.dumps(arr).encode()
                ))
            except (ValueError, KeyError, TypeError,
                    AttributeError) as exc:
                log.debug("ocpp bad dn command for %s: %s",
                          self.cpid, exc)
            # broker-side QoS deliveries settle on handoff (the
            # socket is the terminal hop, like exproto.py) — without
            # this the 32-slot inflight window fills and downstream
            # commands stall forever
            if pkt.packet_id and self.session is not None:
                _ok, follow = self.session.puback(pkt.packet_id)
                if follow:
                    pending.extend(follow)


class OcppGateway(Gateway):
    """WebSocket listener (the reference rides cowboy; here the same
    hand-rolled RFC 6455 server the broker's ws listeners use)."""

    name = "ocpp"
    channel_class = OcppChannel

    def __init__(self, broker, bind: str = "0.0.0.0", port: int = 0,
                 mountpoint: str = "ocpp/", qos: int = 2) -> None:
        super().__init__(broker, bind, port)
        self.mountpoint = mountpoint
        self.qos = max(0, min(int(qos), 2))

    async def _on_client(self, reader, writer) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        peername = writer.get_extra_info("peername")
        peer = f"{peername[0]}:{peername[1]}" if peername else "?"
        closed = asyncio.Event()

        def write(data: bytes) -> None:
            if not writer.is_closing():
                writer.write(data)

        def close(reason: str) -> None:
            if not writer.is_closing():
                writer.close()
            closed.set()

        channel = self.channel_class(self, write, close, peer)
        reason = "closed"
        try:
            path = await asyncio.wait_for(
                server_handshake(
                    reader, writer,
                    accept_protocols=("ocpp1.6", "ocpp1.5"),
                    require_protocol=True,
                ),
                10.0,
            )
            cpid = _cpid_from_path(path)
            if cpid is None or not channel.attach(cpid):
                write(ws_frame(_OP_CLOSE, b"\x03\xe8"))  # 1000
                return
            # WsServerStream does the RFC 6455 legwork (ping/pong,
            # close echo, fragment reassembly, size bound); each
            # read() returns one complete message — exactly an
            # OCPP-J array
            stream = WsServerStream(
                reader, writer,
                max_size=self.broker.config.mqtt.max_packet_size * 2,
            )
            while not closed.is_set():
                data = await stream.read()
                if not data:
                    break
                channel.handle_frame(data)
                await writer.drain()
        except (WsError, asyncio.TimeoutError) as exc:
            reason = f"handshake: {exc}"
        except (asyncio.IncompleteReadError, ConnectionError):
            reason = "peer_reset"
        except asyncio.CancelledError:
            reason = "server_stopped"
        finally:
            channel.connection_lost(reason)
            if not writer.is_closing():
                writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, asyncio.CancelledError):
                pass
            self._conns.discard(task)
