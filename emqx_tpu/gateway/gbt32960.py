"""GB/T 32960 gateway: electric-vehicle terminals bridged to MQTT.

The `emqx_gateway_gbt32960` role (/root/reference/apps/
emqx_gateway_gbt32960/src — frame codec + channel bridging EV
telemetry onto pub/sub); the codec is written from the public GB/T
32960.3-2016 specification:

    frame = "##" cmd(1) ack(1) VIN(17 ascii) encryption(1)
            length(2 BE) body BCC(1, XOR over cmd..body)

Commands handled natively: 0x01 vehicle login (time BCD(6), serial(2),
ICCID(20), battery-pack fields), 0x04 vehicle logout, 0x07/0x08
heartbeat / platform time sync, 0x02 realtime info and 0x03 reissued
(stored) info — realtime bodies decode their vehicle-state block
(speed/mileage/voltage/current/SOC) when present, everything else
passes as hex.  Uplinks publish JSON to ``{mountpoint}{vin}/up``;
platform JSON on ``{mountpoint}{vin}/dn`` ({"cmd", "body_hex"})
frames back with the platform-success ack flag.

Explicit cuts: the encryption byte must be 0x01 (plaintext — RSA/AES
variants rejected), and only the realtime vehicle-state information
type is decoded field-by-field (the other six info types cross as
hex; the reference decodes them via its own per-type codecs)."""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Tuple

from ..access import PUBLISH, SUBSCRIBE, ClientInfo
from ..broker.session import SubOpts
from ..message import Message
from . import Gateway, GatewayChannel, GatewayFrame

MAX_FRAME = 65536

CMD_LOGIN = 0x01
CMD_REALTIME = 0x02
CMD_REISSUE = 0x03
CMD_LOGOUT = 0x04
CMD_HEARTBEAT = 0x07
CMD_TIMESYNC = 0x08

ACK_SUCCESS = 0x01
ACK_COMMAND = 0xFE  # a terminal-originated data frame

ENC_PLAIN = 0x01


class GbtMessage:
    __slots__ = ("cmd", "ack", "vin", "body")

    def __init__(self, cmd: int, ack: int, vin: str,
                 body: bytes = b"") -> None:
        self.cmd = cmd
        self.ack = ack
        self.vin = vin
        self.body = body


class GbtCodec(GatewayFrame):
    def initial_state(self) -> bytes:
        return b""

    def parse(
        self, state: bytes, data: bytes
    ) -> Tuple[List[GbtMessage], bytes]:
        buf = state + data
        if len(buf) > MAX_FRAME * 2:
            raise ValueError("gbt32960: buffer overflow")
        out: List[GbtMessage] = []
        while True:
            start = buf.find(b"##")
            if start < 0:
                return out, buf[-1:] if buf.endswith(b"#") else b""
            buf = buf[start:]
            if len(buf) < 25:
                return out, buf
            cmd, ack = buf[2], buf[3]
            vin = buf[4:21].decode("ascii", "replace").rstrip("\x00 ")
            enc = buf[21]
            (length,) = struct.unpack_from(">H", buf, 22)
            if len(buf) < 25 + length:
                return out, buf
            body = buf[24:24 + length]
            bcc = buf[24 + length]
            check = 0
            for b in buf[2:24 + length]:
                check ^= b
            buf = buf[25 + length:]
            if check != bcc:
                raise ValueError("gbt32960: BCC mismatch")
            if enc != ENC_PLAIN:
                raise ValueError("gbt32960: encrypted frames unsupported")
            out.append(GbtMessage(cmd, ack, vin, body))

    def serialize(self, m: GbtMessage) -> bytes:
        vin = m.vin.encode("ascii", "replace")[:17].ljust(17, b"\x00")
        inner = (
            bytes([m.cmd, m.ack]) + vin + bytes([ENC_PLAIN])
            + struct.pack(">H", len(m.body)) + m.body
        )
        check = 0
        for b in inner:
            check ^= b
        return b"##" + inner + bytes([check])


def _bcd_time(b: bytes) -> str:
    t = b.hex()
    return (f"20{t[0:2]}-{t[2:4]}-{t[4:6]} "
            f"{t[6:8]}:{t[8:10]}:{t[10:12]}")


def decode_realtime(body: bytes) -> Dict:
    """0x02/0x03: time BCD(6) + typed info units; the vehicle-state
    unit (type 0x01) decodes field-by-field, others pass as hex."""
    out: Dict = {"time": _bcd_time(body[:6]), "infos": []}
    off = 6
    while off < len(body):
        itype = body[off]
        off += 1
        if itype == 0x01 and off + 18 <= len(body):
            (state, charge, mode, speed, mileage, voltage, current,
             soc, dcdc, gear, resistance) = struct.unpack_from(
                ">BBBHIHHBBBH", body, off)
            out["infos"].append({
                "type": "vehicle_state",
                "state": state, "charge": charge, "mode": mode,
                "speed_kmh": speed / 10.0,
                "mileage_km": mileage / 10.0,
                "voltage_v": voltage / 10.0,
                "current_a": current / 10.0 - 1000.0,
                "soc_pct": soc,
                "gear": gear & 0x0F,
                "insulation_kohm": resistance,
            })
            off += 18
            # accelerator/brake pedal bytes (2016 edition) when they
            # close the unit out
            if 0 < len(body) - off <= 2:
                off = len(body)
        else:
            # unknown unit: without the per-type length table the rest
            # of the frame crosses as one opaque blob
            out["infos"].append({
                "type": f"raw_{itype:#04x}",
                "hex": body[off:].hex(),
            })
            break
    return out


class GbtChannel(GatewayChannel):
    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.vin: Optional[str] = None
        self.client: Optional[ClientInfo] = None
        self.logged_in = False

    def _reply(self, m: GbtMessage, ack: int = ACK_SUCCESS,
               body: bytes = b"") -> None:
        # platform replies echo the command with its original time
        # body prefix (spec: the ack carries the data unit's time)
        self.write(self.gateway.frame.serialize(GbtMessage(
            m.cmd, ack, m.vin, body or m.body[:6]
        )))

    def _uplink(self, kind: str, m: GbtMessage, extra: Dict) -> None:
        topic = f"{self.gateway.mountpoint}{self.vin}/up"
        if self.client is not None and not self.broker.access.authorize(
            self.client, PUBLISH, topic
        ):
            self.broker.metrics.inc("authorization.deny")
            return
        self.broker_publish(Message(
            topic=topic,
            payload=json.dumps(
                {"cmd": m.cmd, "type": kind, **extra}
            ).encode(),
            qos=self.gateway.qos,
            from_client=f"gbt-{self.vin}",
        ))

    def handle_frame(self, m: GbtMessage) -> None:
        if self.vin is None:
            self.vin = m.vin
        if m.cmd == CMD_LOGIN:
            self._on_login(m)
            return
        if not self.logged_in:
            self._reply(m, ack=0x02)  # error: not logged in
            return
        if m.cmd in (CMD_REALTIME, CMD_REISSUE):
            try:
                info = decode_realtime(m.body)
            except (struct.error, IndexError):
                self._reply(m, ack=0x02)
                return
            kind = "realtime" if m.cmd == CMD_REALTIME else "reissue"
            self._uplink(kind, m, info)
            self._reply(m)
        elif m.cmd == CMD_HEARTBEAT:
            self._reply(m, body=b"")
        elif m.cmd == CMD_LOGOUT:
            self._uplink("logout", m, {"time": _bcd_time(m.body[:6])})
            self._reply(m)
            self.close("logout")
        else:
            self._uplink("raw", m, {"body_hex": m.body.hex()})
            self._reply(m)

    def _on_login(self, m: GbtMessage) -> None:
        client = ClientInfo(clientid=f"gbt-{m.vin}",
                            peerhost=self.peer)
        ok, client = self.broker.access.authenticate(client)
        dn = f"{self.gateway.mountpoint}{m.vin}/dn"
        if not ok or not self.broker.access.authorize(
            client, SUBSCRIBE, dn
        ):
            self._reply(m, ack=0x02)
            return
        self.client = client
        self.logged_in = True
        self.open_session(client.clientid, clean_start=False)
        opts = SubOpts(qos=self.gateway.qos)
        is_new = self.session.subscribe(dn, opts)
        self.broker.subscribe(client.clientid, dn, opts,
                              is_new_sub=is_new)
        body = {"time": _bcd_time(m.body[:6])}
        if len(m.body) >= 8:
            body["serial"] = struct.unpack_from(">H", m.body, 6)[0]
        if len(m.body) >= 28:
            body["iccid"] = m.body[8:28].decode("ascii", "replace")
        self._uplink("login", m, body)
        self._reply(m)

    def deliver(self, packets) -> None:
        for pkt in packets:
            try:
                cmd = json.loads(pkt.payload)
                self.write(self.gateway.frame.serialize(GbtMessage(
                    int(cmd["cmd"]), ACK_COMMAND, self.vin or "",
                    bytes.fromhex(cmd.get("body_hex", "")),
                )))
            except (ValueError, KeyError, UnicodeDecodeError):
                continue


class GbtGateway(Gateway):
    name = "gbt32960"
    frame_class = GbtCodec
    channel_class = GbtChannel

    def __init__(self, broker, bind: str = "0.0.0.0", port: int = 0,
                 mountpoint: str = "gbt32960/", qos: int = 1) -> None:
        super().__init__(broker, bind, port)
        self.mountpoint = mountpoint
        self.qos = qos
