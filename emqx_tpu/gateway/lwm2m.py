"""LwM2M gateway: device management over CoAP, bridged to MQTT.

The `emqx_gateway_lwm2m` role (/root/reference/apps/emqx_gateway_lwm2m/
src/emqx_lwm2m_session.erl:93 `?PREFIX rd`, emqx_lwm2m_cmd.erl:44-196
mqtt_to_coap/coap_to_mqtt): devices register over the OMA LwM2M
registration interface (CoAP POST /rd), the gateway opens an MQTT
session under the endpoint name, and device management flows as JSON
over MQTT topics — commands arrive on the downlink topic
(``lwm2m/{ep}/dn/#``) as ``{"reqID", "msgType":
read|write|execute|discover|observe|cancel-observe, "data": {"path":
"/3/0/0", ...}}``, are translated to CoAP requests to the device, and
responses/notifications are published to the uplink topics
(``up/resp`` / ``up/notify``).

Scope: the registration interface (register/update/deregister), the
device-management command bridge, and observe notifications.  Payloads
cross raw (UTF-8 when possible, base64 otherwise) — the reference's
TLV/JSON content decoding (emqx_lwm2m_tlv.erl) and XML object DB are
not modelled; DTLS is unavailable (Python `ssl` has no DTLS).
"""

from __future__ import annotations

import base64
import json
import logging
import secrets
import time
from typing import Dict, Optional, Tuple

from ..access import ClientInfo
from ..message import Message
from . import GatewayChannel, UdpGateway
from .coap import (
    ACK,
    BAD_REQUEST,
    CHANGED,
    CON,
    CoapCodec,
    CoapMessage,
    CONTENT,
    CREATED,
    DELETE,
    DELETED,
    GET,
    NON,
    NOT_FOUND,
    OPT_CONTENT_FORMAT,
    OPT_OBSERVE,
    OPT_URI_PATH,
    OPT_URI_QUERY,
    POST,
    PUT,
    RST,
    _encode_uint,
)

log = logging.getLogger("emqx_tpu.gateway.lwm2m")

OPT_LOCATION_PATH = 8

# msgType -> CoAP method (emqx_lwm2m_cmd.erl mqtt_to_coap clauses)
_CMD_METHODS = {
    "read": GET,
    "discover": GET,
    "write": PUT,
    "write-attr": PUT,
    "execute": POST,
    "create": POST,
    "delete": DELETE,
    "observe": GET,
    "cancel-observe": GET,
}

_CODE_NAMES = {
    CREATED: "2.01", DELETED: "2.02", 0x43: "2.03", CHANGED: "2.04",
    CONTENT: "2.05", BAD_REQUEST: "4.00", 0x81: "4.01", 0x84: "4.04",
    0x85: "4.05",
}


def _code_name(code: int) -> str:
    return _CODE_NAMES.get(code, f"{code >> 5}.{code & 0x1F:02d}")


def _payload_json(data: bytes):
    """Raw device payload -> JSON-safe value."""
    try:
        return data.decode("utf-8")
    except UnicodeDecodeError:
        return {"base64": base64.b64encode(data).decode()}


# ------------------------------------------------------------ OMA-TLV

TLV_CONTENT_FORMAT = 11542  # application/vnd.oma.lwm2m+tlv

_TLV_OBJ_INST, _TLV_RES_INST, _TLV_MULTI, _TLV_RES = 0, 1, 2, 3


def _tlv_value(v: bytes) -> dict:
    """Typeless resource value: without the OMA object registry the
    concrete type is unknowable, so every plausible reading ships —
    the dm application picks the one its data model says."""
    out: dict = {"hex": v.hex()}
    if len(v) in (1, 2, 4, 8):
        out["int"] = int.from_bytes(v, "big", signed=True)
        if len(v) in (4, 8):
            import struct as _s

            out["float"] = _s.unpack(
                ">f" if len(v) == 4 else ">d", v
            )[0]
    try:
        s = v.decode("utf-8")
        if s.isprintable() or s == "":
            out["str"] = s
    except UnicodeDecodeError:
        pass
    return out


def decode_tlv(data: bytes) -> list:
    """OMA-TLV (LwM2M TS 6.4.3): nested object-instance / resource /
    multiple-resource entries."""
    out = []
    off = 0
    n = len(data)
    while off < n:
        t = data[off]
        off += 1
        kind = (t >> 6) & 0x3
        id_len = 2 if t & 0x20 else 1
        ltype = (t >> 3) & 0x3
        ident = int.from_bytes(data[off:off + id_len], "big")
        off += id_len
        if ltype == 0:
            length = t & 0x7
        else:
            length = int.from_bytes(data[off:off + ltype], "big")
            off += ltype
        if off + length > n:
            raise ValueError("tlv: truncated entry")
        val = data[off:off + length]
        off += length
        if kind == _TLV_OBJ_INST:
            out.append({"kind": "obj_inst", "id": ident,
                        "resources": decode_tlv(val)})
        elif kind == _TLV_MULTI:
            out.append({"kind": "multiple", "id": ident,
                        "instances": decode_tlv(val)})
        else:
            out.append({
                "kind": "res_inst" if kind == _TLV_RES_INST else "res",
                "id": ident,
                "value": _tlv_value(val),
            })
    return out


def _tlv_raw(value) -> bytes:
    if isinstance(value, dict):
        if "hex" in value:
            return bytes.fromhex(value["hex"])
        if "int" in value:
            v = int(value["int"])
            for size in (1, 2, 4, 8):
                if -(1 << (8 * size - 1)) <= v < (1 << (8 * size - 1)):
                    return v.to_bytes(size, "big", signed=True)
        if "str" in value:
            return str(value["str"]).encode()
    if isinstance(value, bool):
        return b"\x01" if value else b"\x00"
    if isinstance(value, int):
        return _tlv_raw({"int": value})
    return str(value).encode()


def encode_tlv(entries: list) -> bytes:
    """Inverse of `decode_tlv` (downlink TLV writes)."""
    out = bytearray()
    for e in entries:
        kind = {"obj_inst": _TLV_OBJ_INST, "res_inst": _TLV_RES_INST,
                "multiple": _TLV_MULTI, "res": _TLV_RES}[e["kind"]]
        if kind == _TLV_OBJ_INST:
            val = encode_tlv(e.get("resources", []))
        elif kind == _TLV_MULTI:
            val = encode_tlv(e.get("instances", []))
        else:
            val = _tlv_raw(e.get("value"))
        ident = int(e["id"])
        t = kind << 6
        id_bytes = (
            ident.to_bytes(2, "big") if ident > 0xFF
            else bytes([ident])
        )
        if len(id_bytes) == 2:
            t |= 0x20
        if len(val) < 8:
            t |= len(val)
            len_bytes = b""
        else:
            for lt, size in ((1, 1), (2, 2), (3, 3)):
                if len(val) < (1 << (8 * size)):
                    t |= lt << 3
                    len_bytes = len(val).to_bytes(size, "big")
                    break
        out += bytes([t]) + id_bytes + len_bytes + val
    return bytes(out)


class Lwm2mChannel(GatewayChannel):
    """One device (one UDP peer): registration state + in-flight
    device-management requests (token -> originating command)."""

    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.codec: CoapCodec = gateway.frame
        self.endpoint: Optional[str] = None
        self.location: Optional[str] = None
        self.lifetime = 86400
        # registered devices stay reachable for their LwM2M lifetime,
        # not the UDP gateway's short idle default (reaper honors this)
        self.idle_deadline: Optional[float] = None
        self._next_mid = secrets.randbelow(0xFFFF)
        # token -> command dict awaiting the device's response
        self._pending: Dict[bytes, dict] = {}
        # observed path -> token (so cancel-observe reuses it)
        self._observes: Dict[str, bytes] = {}

    # ------------------------------------------------------- helpers

    def _alloc_mid(self) -> int:
        self._next_mid = (self._next_mid + 1) % 0x10000
        return self._next_mid

    def _reply(self, req: CoapMessage, code: int, options=None,
               payload: bytes = b"") -> None:
        rtype = ACK if req.type == CON else NON
        mid = req.message_id if req.type == CON else self._alloc_mid()
        self.write(self.codec.serialize(CoapMessage(
            rtype, code, mid, req.token, options or [], payload)))

    def _uplink(self, kind: str, body: dict) -> None:
        """Publish to the mounted uplink topic (translators.response /
        .notify / .register / .update); ACL-checked like every other
        gateway's publish path."""
        from ..access import PUBLISH

        gw = self.gateway
        topic = f"{gw.mountpoint.format(ep=self.endpoint)}" \
                f"{gw.translators.get(kind, 'up/resp')}"
        if not self.broker.access.authorize(self.client, PUBLISH, topic):
            self.broker.metrics.inc("authorization.deny")
            return
        self.broker_publish(Message(
            topic=topic,
            payload=json.dumps(body).encode(),
            qos=gw.qos, from_client=self.clientid,
        ))

    # -------------------------------------------------- registration

    def handle_frame(self, m: CoapMessage) -> None:
        if m.type == RST:
            return
        if m.token and m.token in self._pending:
            self._on_device_response(m)
            return
        if m.type == ACK or m.code == 0:
            if m.type == CON and m.code == 0:
                self.write(self.codec.serialize(
                    CoapMessage(RST, 0, m.message_id, b"")))
            return
        path = m.uri_path
        if not path or path[0] != "rd":
            self._reply(m, NOT_FOUND)
            return
        if m.code == POST and len(path) == 1:
            self._register(m)
        elif m.code == POST and len(path) == 2:
            self._update(m, path[1])
        elif m.code == DELETE and len(path) == 2:
            self._deregister(m, path[1])
        else:
            self._reply(m, BAD_REQUEST)

    def _register(self, m: CoapMessage) -> None:
        q = m.queries
        ep = q.get("ep")
        if not ep:
            self._reply(m, BAD_REQUEST)
            return
        client = ClientInfo(clientid=ep, peerhost=self.peer)
        if self.broker.banned.is_banned(
            clientid=ep, peerhost=self.peer.rsplit(":", 1)[0]
        ):
            self._reply(m, BAD_REQUEST)
            return
        ok, client = self.broker.access.authenticate(client)
        if not ok:
            self._reply(m, 0x81)  # 4.01
            return
        gw = self.gateway
        flt = f"{gw.mountpoint.format(ep=ep)}{gw.translators['command']}"
        from ..access import SUBSCRIBE

        if not self.broker.access.authorize(client, SUBSCRIBE, flt):
            self._reply(m, 0x81)  # 4.01: authenticated but not allowed
            return
        self.client = client
        self.endpoint = ep
        self.lifetime = int(q.get("lt", "86400") or 86400)
        self.idle_deadline = time.monotonic() + self.lifetime * 1.5
        self.location = secrets.token_hex(4)
        self.open_session(ep, clean_start=True)
        # commands for this device arrive on the downlink filter
        from ..broker.session import SubOpts

        opts = SubOpts(qos=gw.qos)
        is_new = self.session.subscribe(flt, opts)
        self.broker.subscribe(ep, flt, opts, is_new_sub=is_new)
        objects = m.payload.decode("utf-8", "replace") if m.payload \
            else ""
        self._uplink("register", {
            "msgType": "register",
            "data": {
                "ep": ep, "lt": self.lifetime,
                "lwm2m": q.get("lwm2m", "1.0"),
                "objectList": [
                    o.strip().strip("<>")
                    for o in objects.split(",") if o.strip()
                ],
            },
        })
        self._reply(m, CREATED, options=[
            (OPT_LOCATION_PATH, b"rd"),
            (OPT_LOCATION_PATH, self.location.encode()),
        ])

    def _update(self, m: CoapMessage, loc: str) -> None:
        if loc != self.location or self.endpoint is None:
            self._reply(m, NOT_FOUND)
            return
        lt = m.queries.get("lt")
        if lt:
            self.lifetime = int(lt)
        self.idle_deadline = time.monotonic() + self.lifetime * 1.5
        self._uplink("update", {
            "msgType": "update",
            "data": {"ep": self.endpoint, "lt": self.lifetime},
        })
        self._reply(m, CHANGED)

    def _deregister(self, m: CoapMessage, loc: str) -> None:
        if loc != self.location:
            self._reply(m, NOT_FOUND)
            return
        self._reply(m, DELETED)
        self.close("deregistered")

    # ------------------------------------------- command bridge (dn)

    def deliver(self, packets) -> None:
        for pkt in packets:
            try:
                cmd = json.loads(pkt.payload)
                self._send_command(cmd)
            except (ValueError, KeyError, TypeError,
                    AttributeError) as exc:
                # malformed command must never escape into the
                # broker's delivery fan-out — error goes back uplink
                log.debug("lwm2m bad command: %s", exc)
                self._uplink("response", {
                    "msgType": "error",
                    "data": {"reason": str(exc)},
                })

    def _send_command(self, cmd: dict) -> None:
        mtype = cmd["msgType"]
        method = _CMD_METHODS[mtype]
        data = cmd.get("data", {})
        path = str(data.get("path", "")).strip("/")
        token = secrets.token_bytes(4)
        options = [(OPT_URI_PATH, seg.encode())
                   for seg in path.split("/") if seg]
        payload = b""
        if mtype == "observe":
            options.append((OPT_OBSERVE, b""))  # register (0)
            # a re-observe of the same path supersedes the old one:
            # drop its pending entry so stale-token notifications stop
            old = self._observes.pop(path, None)
            if old is not None:
                self._pending.pop(old, None)
            self._observes[path] = token
        elif mtype == "cancel-observe":
            options.append((OPT_OBSERVE, _encode_uint(1)))
            token = self._observes.pop(path, token)
        elif mtype in ("write", "create"):
            value = data.get("value", "")
            if isinstance(value, dict) and "tlv" in value:
                # structured write: encode the entries as OMA-TLV
                payload = encode_tlv(value["tlv"])
                options.append((
                    OPT_CONTENT_FORMAT,
                    TLV_CONTENT_FORMAT.to_bytes(2, "big"),
                ))
            else:
                payload = value.encode() if isinstance(value, str) \
                    else json.dumps(value).encode()
                options.append((OPT_CONTENT_FORMAT, b""))  # text/plain
        elif mtype == "execute":
            payload = str(data.get("args", "")).encode()
        elif mtype == "write-attr":
            for attr in ("pmin", "pmax", "gt", "lt", "st"):
                if attr in data:
                    options.append((
                        OPT_URI_QUERY,
                        f"{attr}={data[attr]}".encode(),
                    ))
        elif mtype == "discover":
            pass  # GET with Accept link-format; raw GET suffices here
        self._pending[token] = cmd
        self.write(self.codec.serialize(CoapMessage(
            CON, method, self._alloc_mid(), token, options, payload)))

    def _on_device_response(self, m: CoapMessage) -> None:
        cmd = self._pending.get(m.token)
        if cmd is None:
            return
        if cmd.get("msgType") == "observe":
            # the observe stays pending: every notification reuses the
            # token; the FIRST response answers the command, the rest
            # are notifications (emqx_lwm2m_cmd coap_to_mqtt observe)
            is_notify = bool(cmd.get("_answered"))
            cmd["_answered"] = True
        else:
            is_notify = False
            self._pending.pop(m.token, None)
        # OMA-TLV responses decode to structured resources (the
        # reference's emqx_lwm2m_message tlv path); anything else
        # crosses as text/base64
        content = _payload_json(m.payload)
        cfv = [v for n, v in m.options if n == OPT_CONTENT_FORMAT]
        if cfv and int.from_bytes(cfv[0], "big") == TLV_CONTENT_FORMAT:
            try:
                content = {"tlv": decode_tlv(m.payload)}
            except ValueError:
                pass  # malformed TLV: fall back to the raw form
        body = {
            "reqID": cmd.get("reqID"),
            "msgType": cmd.get("msgType"),
            "data": {
                "code": _code_name(m.code),
                "reqPath": cmd.get("data", {}).get("path"),
                "content": content,
            },
        }
        self._uplink("notify" if is_notify else "response", body)
        if m.type == CON:
            self.write(self.codec.serialize(
                CoapMessage(ACK, 0, m.message_id, b"")))

    def connection_lost(self, reason: str) -> None:
        self._pending.clear()
        super().connection_lost(reason)


class Lwm2mGateway(UdpGateway):
    name = "lwm2m"
    frame_class = CoapCodec
    channel_class = Lwm2mChannel

    def __init__(self, broker, bind: str = "0.0.0.0", port: int = 0,
                 mountpoint: str = "lwm2m/{ep}/",
                 translators: Optional[Dict[str, str]] = None,
                 qos: int = 0) -> None:
        super().__init__(broker, bind, port)
        self.mountpoint = mountpoint
        # relative topics under the mountpoint (gateway.lwm2m.translators)
        self.translators = {
            "command": "dn/#",
            "response": "up/resp",
            "register": "up/resp",
            "update": "up/resp",
            "notify": "up/notify",
            **(translators or {}),
        }
        self.qos = max(0, min(int(qos), 2))
