"""Multi-protocol gateway framework.

The `emqx_gateway` behaviors (/root/reference/apps/emqx_gateway/src/
bhvrs/emqx_gateway_frame.erl:45-63 parse/serialize contract,
emqx_gateway_channel.erl, emqx_gateway_conn.erl): a gateway adapts a
non-MQTT protocol onto the broker's pub/sub core.  Each gateway
supplies a frame codec and a channel class; the framework owns the TCP
accept loop, the read/parse pump, and the session adapter that turns
broker deliveries (MQTT Publish packets) into gateway frames.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Dict, List, Optional, Tuple

from ..aio import cancel_and_wait

log = logging.getLogger("emqx_tpu.gateway")


class GatewayFrame:
    """Frame codec behavior (emqx_gateway_frame parity)."""

    def initial_state(self):
        return b""

    def parse(self, state, data: bytes) -> Tuple[List[object], object]:
        """Consume bytes, return (frames, new_state)."""
        raise NotImplementedError

    def serialize(self, frame) -> bytes:
        raise NotImplementedError


class GatewayChannel:
    """Per-connection protocol handler.  Subclasses implement
    ``handle_frame``; ``deliver`` receives broker deliveries (MQTT
    Publish packets via the session adapter) to re-frame for the
    client."""

    def __init__(self, gateway: "Gateway", write, close, peer: str) -> None:
        self.gateway = gateway
        self.broker = gateway.broker
        self.write = write  # callable(bytes)
        self.close = close  # callable(reason)
        self.peer = peer
        self.clientid: Optional[str] = None
        self.session = None

    def handle_frame(self, frame) -> None:
        raise NotImplementedError

    def deliver(self, publishes) -> None:
        raise NotImplementedError

    def connection_lost(self, reason: str) -> None:
        if self.clientid is not None and self.session is not None:
            self.broker.cm.disconnect(self.clientid, self._adapter)
            if self.session.expiry_interval <= 0:
                self.broker.session_terminated(self.clientid, self.session)
            self.session = None

    # --------------------------------------------------- broker glue

    def broker_publish(self, msg) -> None:
        """Publish through the shared micro-batcher when one is running
        (one device match step per window), else synchronously."""
        batcher = self.broker.batcher
        if batcher is not None:
            batcher.publish_nowait(msg)
        else:
            self.broker.publish(msg)

    def open_session(self, clientid: str, clean_start: bool = True):
        """Register with the broker's connection manager; deliveries
        route back through this channel."""
        channel = self

        class _Adapter:
            """ChannelLike: broker-side deliveries + kicks land here."""

            @staticmethod
            def send_packets(packets) -> None:
                channel.deliver(packets)

            @staticmethod
            def close(reason: str) -> None:
                channel.close(reason)

        from ..broker.resume import ResumeBusy

        self._adapter = _Adapter()
        try:
            session, present = self.broker.open_session(
                clean_start, clientid, self._adapter
            )
        except ResumeBusy as exc:
            # gateway protocols have no CONNACK server-busy: refuse
            # the connect (the transport closes; devices retry)
            channel.close("resume_busy")
            raise ConnectionError("resume admission saturated") from exc
        self.clientid = clientid
        self.session = session
        self.broker.metrics.inc(f"gateway.{self.gateway.name}.connected")
        return session, present


class Gateway:
    """One configured gateway instance: a frame codec, a channel class,
    and a TCP listener."""

    name = "abstract"
    frame_class = GatewayFrame
    channel_class = GatewayChannel

    def __init__(
        self, broker, bind: str = "0.0.0.0", port: int = 0
    ) -> None:
        self.broker = broker
        self.bind = bind
        self.port = port
        self.frame: GatewayFrame = self.frame_class()
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_client, self.bind, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("gateway %s listening on %s:%d", self.name, self.bind,
                 self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in list(self._conns):
            task.cancel()
        if self._conns:
            await asyncio.gather(*self._conns, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        peername = writer.get_extra_info("peername")
        peer = f"{peername[0]}:{peername[1]}" if peername else "?"
        closed = asyncio.Event()

        def write(data: bytes) -> None:
            if not writer.is_closing():
                writer.write(data)

        def close(reason: str) -> None:
            if not writer.is_closing():
                writer.close()
            closed.set()

        channel = self.channel_class(self, write, close, peer)
        state = self.frame.initial_state()
        reason = "closed"
        try:
            while not closed.is_set():
                data = await reader.read(65536)
                if not data:
                    break
                try:
                    frames, state = self.frame.parse(state, data)
                except ValueError as exc:
                    log.debug("gateway %s frame error: %s", self.name, exc)
                    reason = "frame_error"
                    break
                for frame in frames:
                    channel.handle_frame(frame)
                    if closed.is_set():
                        break
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            reason = "peer_reset"
        except asyncio.CancelledError:
            reason = "server_stopped"
        finally:
            self._conns.discard(task)
            channel.connection_lost(reason)
            if not writer.is_closing():
                writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, asyncio.CancelledError):
                pass


class UdpGateway(Gateway):
    """Datagram gateway base (the `emqx_gateway_conn` UDP side,
    /root/reference/apps/emqx_gateway/src/emqx_gateway_conn.erl:120-141
    esockd udp_proxy role): one socket, one channel per peer address,
    idle peers expired after ``idle_timeout_s``.

    Datagram protocols frame per-packet, so ``frame.parse`` is called
    with exactly one datagram and must consume it whole."""

    idle_timeout_s = 120.0
    max_channels = 65536  # spoofed-source flood ceiling

    def __init__(self, broker, bind: str = "0.0.0.0", port: int = 0) -> None:
        super().__init__(broker, bind, port)
        self._channels: Dict[Tuple[str, int], GatewayChannel] = {}
        self._last_seen: Dict[Tuple[str, int], float] = {}
        self._transport = None
        self._reaper: Optional[asyncio.Task] = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        gateway = self

        class _Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                gateway._transport = transport

            def datagram_received(self, data, addr):
                gateway._on_datagram(data, addr)

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(self.bind, self.port)
        )
        self.port = self._transport.get_extra_info("sockname")[1]
        self._reaper = asyncio.get_running_loop().create_task(
            self._reap_idle()
        )
        log.info("udp gateway %s listening on %s:%d", self.name, self.bind,
                 self.port)

    async def stop(self) -> None:
        if self._reaper is not None:
            await cancel_and_wait(self._reaper)
            self._reaper = None
        for addr in list(self._channels):
            self._drop_peer(addr, "server_stopped")
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    def _on_datagram(self, data: bytes, addr) -> None:
        # parse BEFORE allocating per-peer state: spoofed-source garbage
        # must not grow the channel table
        try:
            frames, _ = self.frame.parse(self.frame.initial_state(), data)
        except (ValueError, IndexError, struct.error) as exc:
            log.debug("udp gateway %s frame error from %s: %s",
                      self.name, addr, exc)
            return
        chan = self._channels.get(addr)
        if chan is None:
            if len(self._channels) >= self.max_channels:
                log.debug("udp gateway %s at channel cap; dropping %s",
                          self.name, addr)
                return
            peer = f"{addr[0]}:{addr[1]}"
            gateway = self

            def write(out: bytes, _addr=addr) -> None:
                if gateway._transport is not None:
                    gateway._transport.sendto(out, _addr)

            def close(reason: str, _addr=addr) -> None:
                gateway._drop_peer(_addr, reason)

            chan = self.channel_class(self, write, close, peer)
            self._channels[addr] = chan
        self._last_seen[addr] = time.monotonic()
        for frame in frames:
            try:
                chan.handle_frame(frame)
            except (ValueError, IndexError, struct.error) as exc:
                log.debug("udp gateway %s handler error from %s: %s",
                          self.name, addr, exc)

    def _drop_peer(self, addr, reason: str) -> None:
        chan = self._channels.pop(addr, None)
        self._last_seen.pop(addr, None)
        if chan is not None:
            chan.connection_lost(reason)

    async def _reap_idle(self) -> None:
        while True:
            await asyncio.sleep(min(self.idle_timeout_s / 4, 30.0))
            now = time.monotonic()
            cutoff = now - self.idle_timeout_s
            for addr, seen in list(self._last_seen.items()):
                chan = self._channels.get(addr)
                # a channel may extend its own lifetime (MQTT-SN
                # sleeping clients announce a sleep duration)
                deadline = getattr(chan, "idle_deadline", None)
                try:
                    if deadline is not None:
                        if now > deadline:
                            self._drop_peer(addr, "idle_timeout")
                    elif seen < cutoff:
                        self._drop_peer(addr, "idle_timeout")
                except Exception:
                    # one bad channel must not kill the shared reaper
                    # (that would leak every future idle peer)
                    log.exception("udp gateway %s: drop of %s failed",
                                  self.name, addr)


class GatewayRegistry:
    """Named gateway instances bound to one broker (the emqx_gateway
    registry/lifecycle role)."""

    def __init__(self, broker) -> None:
        self.broker = broker
        self._gateways: Dict[str, Gateway] = {}

    async def load(self, gateway: Gateway) -> Gateway:
        await gateway.start()
        self._gateways[gateway.name] = gateway
        return gateway

    def get(self, name: str) -> Optional[Gateway]:
        return self._gateways.get(name)

    async def unload(self, name: str) -> bool:
        gw = self._gateways.pop(name, None)
        if gw is None:
            return False
        await gw.stop()
        return True

    async def stop_all(self) -> None:
        for name in list(self._gateways):
            await self.unload(name)

    def info(self) -> List[Dict]:
        return [
            {"name": n, "port": g.port, "bind": g.bind}
            for n, g in self._gateways.items()
        ]
