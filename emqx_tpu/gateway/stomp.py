"""STOMP 1.2 gateway: STOMP clients speak to the MQTT broker core.

The `emqx_gateway_stomp` role (/root/reference/apps/emqx_gateway_stomp/
src/emqx_stomp_frame.erl grammar comment :35-67, emqx_stomp_channel.erl
command handling); the codec is written from the public STOMP 1.2
specification:

    frame   = command EOL *(header EOL) EOL body NUL
    client  : CONNECT/STOMP SEND SUBSCRIBE UNSUBSCRIBE ACK NACK DISCONNECT
    server  : CONNECTED MESSAGE RECEIPT ERROR

Mapping onto the broker: destination == topic (MQTT wildcards pass
through), SEND -> publish, SUBSCRIBE id:ack-mode -> broker subscription
(``auto`` = QoS0, ``client``/``client-individual`` = QoS1 where ACK
acks the delivery), MESSAGE carries subscription + message-id headers.
"""

from __future__ import annotations

import secrets
from typing import Dict, List, Optional, Tuple

from ..access import PUBLISH, SUBSCRIBE, ClientInfo
from ..broker.session import SubOpts
from ..codec import mqtt as C
from ..message import Message
from . import Gateway, GatewayChannel, GatewayFrame

EOL = b"\n"
NUL = b"\x00"
MAX_FRAME = 1 << 20


class StompFrame:
    __slots__ = ("command", "headers", "body")

    def __init__(
        self,
        command: str,
        headers: Optional[Dict[str, str]] = None,
        body: bytes = b"",
    ) -> None:
        self.command = command
        self.headers = headers or {}
        self.body = body


_ESCAPES = {"\\n": "\n", "\\c": ":", "\\\\": "\\", "\\r": "\r"}


def _unescape(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        pair = value[i : i + 2]
        if pair in _ESCAPES:
            out.append(_ESCAPES[pair])
            i += 2
        else:
            out.append(value[i])
            i += 1
    return "".join(out)


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace(":", "\\c")
        .replace("\r", "\\r")
    )


class StompCodec(GatewayFrame):
    def initial_state(self) -> bytes:
        return b""

    def parse(self, state: bytes, data: bytes) -> Tuple[List[StompFrame], bytes]:
        buf = state + data
        if len(buf) > MAX_FRAME:
            raise ValueError("stomp frame too large")
        frames: List[StompFrame] = []
        while buf:
            # bare EOLs between frames are heartbeats
            if buf[0:1] in (b"\n", b"\r"):
                buf = buf.lstrip(b"\r\n")
                continue
            head_end = buf.find(b"\n\n")
            crlf = False
            alt = buf.find(b"\r\n\r\n")
            if alt != -1 and (head_end == -1 or alt < head_end):
                head_end, crlf = alt, True
            if head_end == -1:
                break
            header_blob = buf[:head_end].decode("utf-8", "replace")
            body_start = head_end + (4 if crlf else 2)
            lines = [
                ln.rstrip("\r") for ln in header_blob.split("\n")
            ]
            command = lines[0].strip()
            headers: Dict[str, str] = {}
            for ln in lines[1:]:
                if ":" not in ln:
                    continue
                k, v = ln.split(":", 1)
                headers.setdefault(_unescape(k), _unescape(v))
            if "content-length" in headers:
                n = int(headers["content-length"])
                if len(buf) < body_start + n + 1:
                    break
                body = buf[body_start : body_start + n]
                if buf[body_start + n : body_start + n + 1] != NUL:
                    raise ValueError("stomp frame missing NUL after body")
                buf = buf[body_start + n + 1 :]
            else:
                nul = buf.find(NUL, body_start)
                if nul == -1:
                    break
                body = buf[body_start:nul]
                buf = buf[nul + 1 :]
            frames.append(StompFrame(command, headers, body))
        return frames, buf

    def serialize(self, frame: StompFrame) -> bytes:
        out = [frame.command.encode()]
        headers = dict(frame.headers)
        if frame.body:
            headers.setdefault("content-length", str(len(frame.body)))
        for k, v in headers.items():
            out.append(f"{_escape(k)}:{_escape(str(v))}".encode())
        return EOL.join(out) + b"\n\n" + frame.body + NUL


class StompChannel(GatewayChannel):
    def __init__(self, gateway, write, close, peer) -> None:
        super().__init__(gateway, write, close, peer)
        self.connected = False
        # subscription id -> (topic, ack_mode)
        self._subs: Dict[str, Tuple[str, str]] = {}
        self._topic_sub: Dict[str, str] = {}  # topic -> sub id
        self.client: Optional[ClientInfo] = None

    # ------------------------------------------------------- outgoing

    def _send(self, frame: StompFrame) -> None:
        self.write(self.gateway.frame.serialize(frame))

    def _error(self, message: str, detail: str = "") -> None:
        self._send(
            StompFrame(
                "ERROR", {"message": message}, detail.encode()
            )
        )
        self.close("stomp_error")

    def _receipt(self, headers: Dict[str, str]) -> None:
        rid = headers.get("receipt")
        if rid is not None:
            self._send(StompFrame("RECEIPT", {"receipt-id": rid}))

    # ------------------------------------------------------- incoming

    def handle_frame(self, frame: StompFrame) -> None:
        cmd = frame.command
        if not self.connected:
            if cmd in ("CONNECT", "STOMP"):
                self._handle_connect(frame)
            else:
                self._error("not connected")
            return
        if cmd == "SEND":
            self._handle_send(frame)
        elif cmd == "SUBSCRIBE":
            self._handle_subscribe(frame)
        elif cmd == "UNSUBSCRIBE":
            self._handle_unsubscribe(frame)
        elif cmd in ("ACK", "NACK"):
            self._handle_ack(frame, cmd == "ACK")
        elif cmd == "DISCONNECT":
            self._receipt(frame.headers)
            self.close("normal")
        elif cmd in ("BEGIN", "COMMIT", "ABORT"):
            # transactions are accepted but not batched (receipt only)
            self._receipt(frame.headers)
        else:
            self._error(f"unsupported command {cmd}")

    def _handle_connect(self, frame: StompFrame) -> None:
        login = frame.headers.get("login")
        passcode = frame.headers.get("passcode")
        clientid = "stomp-" + (login or secrets.token_hex(6))
        client = ClientInfo(
            clientid=clientid,
            username=login,
            password=passcode.encode() if passcode else None,
            peerhost=self.peer,
        )
        if self.broker.banned.is_banned(
            clientid=clientid, username=login,
            peerhost=self.peer.rsplit(":", 1)[0],
        ):
            self._error("banned")
            return
        ok, client = self.broker.access.authenticate(client)
        if not ok:
            self._error("authentication failed")
            return
        client.password = None
        self.client = client
        self.open_session(clientid, clean_start=True)
        self.connected = True
        self._send(
            StompFrame(
                "CONNECTED",
                {
                    "version": "1.2",
                    "server": "emqx_tpu",
                    "heart-beat": "0,0",
                    "session": clientid,
                },
            )
        )

    def _handle_send(self, frame: StompFrame) -> None:
        dest = frame.headers.get("destination")
        if not dest:
            self._error("SEND requires destination")
            return
        if not self.broker.access.authorize(self.client, PUBLISH, dest):
            self._error("publish not authorized", dest)
            return
        msg = Message(
            topic=dest,
            payload=frame.body,
            qos=int(frame.headers.get("qos", 0)),
            retain=frame.headers.get("retain") == "true",
            from_client=self.clientid,
            from_username=self.client.username,
        )
        self.broker_publish(msg)
        self._receipt(frame.headers)

    def _handle_subscribe(self, frame: StompFrame) -> None:
        dest = frame.headers.get("destination")
        sid = frame.headers.get("id")
        if not dest or sid is None:
            self._error("SUBSCRIBE requires destination and id")
            return
        if not self.broker.access.authorize(self.client, SUBSCRIBE, dest):
            self._error("subscribe not authorized", dest)
            return
        ack_mode = frame.headers.get("ack", "auto")
        qos = 0 if ack_mode == "auto" else 1
        opts = SubOpts(qos=qos)
        is_new = self.session.subscribe(dest, opts)
        self.broker.subscribe(self.clientid, dest, opts, is_new_sub=is_new)
        self._subs[sid] = (dest, ack_mode)
        self._topic_sub[dest] = sid
        self._receipt(frame.headers)

    def _handle_unsubscribe(self, frame: StompFrame) -> None:
        sid = frame.headers.get("id")
        sub = self._subs.pop(sid, None)
        if sub is not None:
            dest, _ = sub
            # several STOMP subscription ids may share one destination:
            # the broker subscription lives until the LAST one goes
            if not any(d == dest for d, _m in self._subs.values()):
                self._topic_sub.pop(dest, None)
                self.session.unsubscribe(dest)
                self.broker.unsubscribe(self.clientid, dest)
            elif self._topic_sub.get(dest) == sid:
                self._topic_sub[dest] = next(
                    s for s, (d, _m) in self._subs.items() if d == dest
                )
        self._receipt(frame.headers)

    def _handle_ack(self, frame: StompFrame, positive: bool) -> None:
        try:
            pid = int(frame.headers.get("id", ""))
        except ValueError:
            self._receipt(frame.headers)
            return
        if positive and self.session is not None:
            # settle the QoS1 delivery AND frame any messages the freed
            # inflight slot dequeues (the MQTT channel's follow-ups)
            _ok, follow_ups = self.session.puback(pid)
            if follow_ups:
                self.deliver(follow_ups)
        self._receipt(frame.headers)

    # ------------------------------------------------------ deliveries

    def deliver(self, packets) -> None:
        """Broker deliveries arrive as MQTT packets (Publish/Pubrel);
        re-frame Publishes as MESSAGE."""
        for pkt in packets:
            if pkt.type != C.PUBLISH:
                continue
            sid = self._topic_sub.get(pkt.topic)
            if sid is None:
                # wildcard subscriptions: find the matching filter
                from .. import topic as T

                for s, (flt, _mode) in self._subs.items():
                    if T.match(pkt.topic, flt):
                        sid = s
                        break
            headers = {
                "destination": pkt.topic,
                "subscription": sid or "0",
                "message-id": str(pkt.packet_id or 0),
            }
            if pkt.packet_id:
                headers["ack"] = str(pkt.packet_id)
            self._send(StompFrame("MESSAGE", headers, pkt.payload))


class StompGateway(Gateway):
    name = "stomp"
    frame_class = StompCodec
    channel_class = StompChannel
