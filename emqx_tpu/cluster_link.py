"""Cluster linking: route-aware federation between independent clusters.

Capability match for `emqx_cluster_link`
(/root/reference/apps/emqx_cluster_link/src/emqx_cluster_link.erl
external-broker behavior, emqx_cluster_link_router_syncer.erl
route-op push, emqx_cluster_link_extrouter.erl remote-interest table):
two clusters exchange *routes first*, so only messages some remote
subscriber actually wants ever cross the link.

Transport rides the ordinary MQTT surface (the reference does the
same — its link agent is an MQTT client on the remote cluster):

  * ``$LINK/route/{cluster}``  — route ops pushed BY cluster
    ``{cluster}``'s agent to this broker: add/del/reset of the topic
    filters that cluster currently has local subscribers for.
  * ``$LINK/msg/{cluster}``    — wrapped messages this broker forwards
    TO cluster ``{cluster}``; its agent subscribes to exactly this
    topic over the link connection.

Loop prevention is by origin tagging (the reference's
`emqx_cluster_link:should_route_to_external_dests` dest-check): a
message carries its origin cluster end-to-end; it is never forwarded
back to its origin, so even cyclic link topologies cannot echo.

Both halves live here:
  * `LinkAgent`   — local side of one configured link: pushes route
    ops for local-interest filters (gated by the link's topic
    allowlist) and imports wrapped messages.
  * `LinkServer`  — accepts route ops from remote agents and forwards
    matching local publishes, via one ``message.publish`` hook.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Dict, List, Optional, Sequence, Set

from . import topic as T
from .client import MqttClient
from .message import Message

log = logging.getLogger("emqx_tpu.cluster_link")

ROUTE_PREFIX = "$LINK/route/"
MSG_PREFIX = "$LINK/msg/"


def filters_intersect(a: str, b: str) -> bool:
    """True when two topic filters can match a common topic
    (the reference's topic intersection, emqx_topic:intersection/2)."""
    aw, bw = T.words(a), T.words(b)
    i = 0
    while True:
        a_end, b_end = i >= len(aw), i >= len(bw)
        if a_end and b_end:
            return True
        if a_end:
            return list(bw[i:]) == ["#"]
        if b_end:
            return list(aw[i:]) == ["#"]
        x, y = aw[i], bw[i]
        if x == "#" or y == "#":
            return True
        if x != y and x != "+" and y != "+":
            return False
        i += 1


def _wrap(msg: Message, origin: str) -> bytes:
    return json.dumps({
        "t": msg.topic,
        "p": base64.b64encode(msg.payload).decode(),
        "q": msg.qos,
        "r": msg.retain,
        "o": origin,
        "c": msg.from_client,
    }).encode()


def _unwrap(payload: bytes) -> Optional[Message]:
    try:
        d = json.loads(payload)
        return Message(
            topic=d["t"],
            payload=base64.b64decode(d["p"]),
            qos=int(d.get("q", 0)),
            retain=bool(d.get("r", False)),
            from_client=d.get("c", ""),
            headers={"cluster_origin": d.get("o", "?")},
        )
    except (ValueError, KeyError, TypeError):
        return None


class LinkAgent:
    """Local half of one configured link (the reference's
    emqx_cluster_link_router_syncer + msg import actor)."""

    def __init__(
        self,
        broker,
        local_cluster: str,
        name: str,  # remote cluster name
        host: str,
        port: int,
        topics: Sequence[str],
        username: Optional[str] = None,
        password: Optional[bytes] = None,
    ) -> None:
        self.broker = broker
        self.local_cluster = local_cluster
        self.name = name
        self.topics = list(topics)
        self._pushed: Set[str] = set()
        self.client = MqttClient(
            host, port, f"$link-{local_cluster}-{name}",
            username=username, password=password,
        )
        self.client.on_message = self._on_remote
        self._ops: asyncio.Queue = asyncio.Queue()
        self._pusher: Optional[asyncio.Task] = None

    # ------------------------------------------------------ lifecycle

    async def start(self) -> None:
        await self.client.subscribe(MSG_PREFIX + self.local_cluster, qos=1)
        # every (re)connect pushes a full resync: the remote may have
        # restarted with an empty extern-route table, and a silent gap
        # would permanently stop forwarding
        self.client.on_connect = lambda: self._ops.put_nowait(
            ("reset", None)
        )
        await self.client.start()
        self._pusher = asyncio.get_running_loop().create_task(
            self._push_loop()
        )

    async def stop(self) -> None:
        if self._pusher is not None:
            self._pusher.cancel()
            try:
                await self._pusher
            except asyncio.CancelledError:
                pass
            self._pusher = None
        await self.client.stop()

    # ----------------------------------------------------- route sync

    def relevant(self, flt: str) -> bool:
        return any(filters_intersect(flt, t) for t in self.topics)

    def route_added(self, flt: str) -> None:
        if not flt.startswith("$") and self.relevant(flt):
            self._ops.put_nowait(("add", flt))

    def route_removed(self, flt: str) -> None:
        if not flt.startswith("$") and self.relevant(flt):
            self._ops.put_nowait(("del", flt))

    def _current_filters(self) -> List[str]:
        router = self.broker.router
        out = set()
        for flt in list(router._subs) + list(router._shared_opts):
            if not flt.startswith("$") and self.relevant(flt):
                out.add(flt)
        return sorted(out)

    async def _push_loop(self) -> None:
        """Serialize route ops onto the link connection; a reconnect
        collapses the queue into one reset (full resync)."""
        topic = ROUTE_PREFIX + self.local_cluster
        while True:
            op, flt = await self._ops.get()
            try:
                if op == "reset":
                    await self.client.connected.wait()
                    filters = self._current_filters()
                    self._pushed = set(filters)
                    body = {"op": "reset", "filters": filters}
                else:
                    if (op == "add") == (flt in self._pushed):
                        continue  # dedup repeated adds/dels
                    await self.client.connected.wait()
                    (self._pushed.add if op == "add"
                     else self._pushed.discard)(flt)
                    body = {"op": op, "filters": [flt]}
                await self.client.publish(
                    topic, json.dumps(body).encode(), qos=1
                )
            except (ConnectionError, asyncio.TimeoutError):
                # link dropped mid-push: full resync once it's back
                while not self._ops.empty():
                    self._ops.get_nowait()
                self._ops.put_nowait(("reset", None))
                await asyncio.sleep(0.2)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("cluster link %s: route push failed",
                              self.name)

    # -------------------------------------------------- message import

    def _on_remote(self, msg: Message) -> None:
        inner = _unwrap(msg.payload)
        if inner is None:
            log.warning("cluster link %s: malformed wrapped message",
                        self.name)
            return
        if inner.headers.get("cluster_origin") == self.local_cluster:
            return  # never re-import our own traffic
        self.broker.metrics.inc("cluster_link.ingress")
        self.broker.publish(inner)


class LinkServer:
    """Remote-interest table + forwarder (the reference's extrouter +
    external-broker forward hook)."""

    def __init__(self, broker, local_cluster: str,
                 allowed: Optional[Set[str]] = None) -> None:
        self.broker = broker
        self.local_cluster = local_cluster
        # route ops are only honored for known peer clusters — without
        # this gate ANY client could push {"op":"reset","filters":["#"]}
        # under a cluster name of its choosing and siphon every publish
        # past per-topic ACLs via its own $LINK/msg subscription
        self.allowed: Set[str] = set(allowed or ())
        # remote cluster -> filters it currently wants
        self.extern_routes: Dict[str, Set[str]] = {}
        self._hook = None

    def start(self) -> None:
        self._hook = self.broker.hooks.add(
            "message.publish", self._on_publish, priority=-60
        )

    def stop(self) -> None:
        if self._hook is not None:
            self.broker.hooks.delete("message.publish", self._hook)
            self._hook = None

    # ---------------------------------------------------------- hook

    def _on_publish(self, msg: Message):
        topic = msg.topic
        if topic.startswith(ROUTE_PREFIX):
            self._route_op(topic[len(ROUTE_PREFIX):], msg.payload)
            return None
        if topic.startswith("$"):  # $LINK/msg, $SYS, ... never forward
            return None
        origin = msg.headers.get("cluster_origin")
        for cluster, filters in self.extern_routes.items():
            if cluster == origin:
                continue  # loop prevention: never send back to origin
            if any(T.match(topic, f) for f in filters):
                self.broker.metrics.inc("cluster_link.egress")
                self.broker.publish(Message(
                    topic=MSG_PREFIX + cluster,
                    payload=_wrap(msg, origin or self.local_cluster),
                    qos=1,
                ))
        return None

    def _route_op(self, cluster: str, payload: bytes) -> None:
        if cluster not in self.allowed:
            log.warning("cluster link: route op for unconfigured peer "
                        "%r ignored", cluster)
            return
        try:
            body = json.loads(payload)
            op = body["op"]
            filters = [str(f) for f in body.get("filters", [])]
        except (ValueError, KeyError, TypeError):
            log.warning("cluster link: malformed route op from %r", cluster)
            return
        routes = self.extern_routes.setdefault(cluster, set())
        if op == "reset":
            routes.clear()
            routes.update(filters)
        elif op == "add":
            routes.update(filters)
        elif op == "del":
            routes.difference_update(filters)
        log.debug("cluster link: %s now wants %d filters",
                  cluster, len(routes))


class ClusterLinks:
    """All configured links of one broker + the serving half."""

    def __init__(self, broker, local_cluster: str,
                 links: Sequence[dict]) -> None:
        self.broker = broker
        # configured link names are the peers whose route ops we honor;
        # an `accept_from` entry extends the set for asymmetric setups
        allowed = {l["name"] for l in links}
        for l in links:
            allowed.update(l.get("accept_from", ()))
        self.server = LinkServer(broker, local_cluster, allowed)
        self.agents = [
            LinkAgent(
                broker,
                local_cluster,
                name=l["name"],
                host=l.get("host", "127.0.0.1"),
                port=int(l.get("port", 1883)),
                topics=l.get("topics", ["#"]),
                username=l.get("username"),
                password=(l["password"].encode()
                          if l.get("password") else None),
            )
            for l in links
        ]
        self._prev_added = None
        self._prev_removed = None

    async def start(self) -> None:
        self.server.start()
        router = self.broker.router
        # chain (don't clobber) the cluster node's route hooks
        self._prev_added = router.on_route_added
        self._prev_removed = router.on_route_removed

        def added(flt, _prev=self._prev_added):
            if _prev is not None:
                _prev(flt)
            for a in self.agents:
                a.route_added(flt)

        def removed(flt, _prev=self._prev_removed):
            if _prev is not None:
                _prev(flt)
            for a in self.agents:
                a.route_removed(flt)

        router.on_route_added = added
        router.on_route_removed = removed
        for a in self.agents:
            await a.start()

    async def stop(self) -> None:
        for a in self.agents:
            await a.stop()
        self.server.stop()
        self.broker.router.on_route_added = self._prev_added
        self.broker.router.on_route_removed = self._prev_removed

    def info(self) -> dict:
        return {
            "links": [
                {
                    "name": a.name,
                    "topics": a.topics,
                    "connected": a.client.connected.is_set(),
                    "pushed_routes": len(a._pushed),
                }
                for a in self.agents
            ],
            "extern_routes": {
                c: sorted(f) for c, f in self.server.extern_routes.items()
            },
        }
