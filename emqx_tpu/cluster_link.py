"""Cluster linking: route-aware federation between independent clusters.

Capability match for `emqx_cluster_link`
(/root/reference/apps/emqx_cluster_link/src/emqx_cluster_link.erl
external-broker behavior, emqx_cluster_link_router_syncer.erl
route-op push, emqx_cluster_link_extrouter.erl remote-interest table):
two clusters exchange *routes first*, so only messages some remote
subscriber actually wants ever cross the link.

Transport rides the ordinary MQTT surface (the reference does the
same — its link agent is an MQTT client on the remote cluster):

  * ``$LINK/route/{cluster}``  — route ops pushed BY cluster
    ``{cluster}``'s agent to this broker: add/del/reset of the topic
    filters that cluster currently has local subscribers for.
  * ``$LINK/msg/{cluster}``    — wrapped messages this broker forwards
    TO cluster ``{cluster}``; its agent subscribes to exactly this
    topic over the link connection.

Loop prevention follows the reference's "no gossip message
forwarding" rule (emqx_cluster_link.erl:86-89 forward/1): only
LOCALLY-originated publishes are ever exported; a link-imported
message (it carries a `cluster_origin` header end-to-end) is
delivered locally and never re-exported. Cyclic topologies therefore
cannot echo or storm — and, as in the reference, transitive relay
through a middle cluster is deliberately unsupported: in a chain
A—B—C, subscribers on C do not see A's publishes unless A and C are
linked directly (full-mesh the clusters that need to interoperate).

Both halves live here:
  * `LinkAgent`   — local side of one configured link: pushes route
    ops for local-interest filters (gated by the link's topic
    allowlist) and imports wrapped messages.
  * `LinkServer`  — accepts route ops from remote agents and forwards
    matching local publishes, via one ``message.publish`` hook.

Compatibility note: agent identity is ``$link:{cluster}:{name}``
(':'-separated). Earlier builds used '-' separators, which are
ambiguous for cluster names containing '-'; both ends of a link must
run a build with the same scheme.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Dict, List, Optional, Sequence, Set

from . import failpoints
from . import topic as T
from .aio import cancel_and_wait
from .client import MqttClient
from .message import Message

log = logging.getLogger("emqx_tpu.cluster_link")

ROUTE_PREFIX = "$LINK/route/"
MSG_PREFIX = "$LINK/msg/"


def filters_intersect(a: str, b: str) -> bool:
    """True when two topic filters can match a common topic
    (the reference's topic intersection, emqx_topic:intersection/2)."""
    aw, bw = T.words(a), T.words(b)
    i = 0
    while True:
        a_end, b_end = i >= len(aw), i >= len(bw)
        if a_end and b_end:
            return True
        if a_end:
            return list(bw[i:]) == ["#"]
        if b_end:
            return list(aw[i:]) == ["#"]
        x, y = aw[i], bw[i]
        if x == "#" or y == "#":
            return True
        if x != y and x != "+" and y != "+":
            return False
        i += 1


def _wrap(msg: Message, origin: str,
          trace: Optional[str] = None) -> bytes:
    out = {
        "t": msg.topic,
        "p": base64.b64encode(msg.payload).decode(),
        "q": msg.qos,
        "r": msg.retain,
        "o": origin,
        "c": msg.from_client,
    }
    if trace:
        # lifecycle trace context ("<trace32>-<link.forward span16>"),
        # the same v5-user-property-shaped value the cluster forward
        # wire carries: the importing broker's spans parent to this
        # link's forward span
        out["x"] = trace
    return json.dumps(out).encode()


def _unwrap(payload: bytes) -> Optional[Message]:
    try:
        d = json.loads(payload)
        headers = {"cluster_origin": d.get("o", "?")}
        if d.get("x"):
            # broker-internal header, adopted (and popped) by the
            # importing broker's publish ingress when ITS tracing is
            # on; never serialized toward subscribers either way
            headers["trace_ctx"] = str(d["x"])
        return Message(
            topic=d["t"],
            payload=base64.b64decode(d["p"]),
            qos=int(d.get("q", 0)),
            retain=bool(d.get("r", False)),
            from_client=d.get("c", ""),
            headers=headers,
        )
    except (ValueError, KeyError, TypeError):
        return None


class LinkAgent:
    """Local half of one configured link (the reference's
    emqx_cluster_link_router_syncer + msg import actor)."""

    def __init__(
        self,
        broker,
        local_cluster: str,
        name: str,  # remote cluster name
        host: str,
        port: int,
        topics: Sequence[str],
        username: Optional[str] = None,
        password: Optional[bytes] = None,
    ) -> None:
        self.broker = broker
        self.local_cluster = local_cluster
        self.name = name
        self.topics = list(topics)
        self._pushed: Set[str] = set()
        # ':' separates the identity fields unambiguously — with '-' a
        # peer named "us" and one named "us-east" would have
        # indistinguishable agent prefixes, letting one configured
        # peer's agent pass as another's
        self.client = MqttClient(
            host, port, f"$link:{local_cluster}:{name}",
            username=username, password=password,
        )
        self.client.on_message = self._on_remote
        self._ops: asyncio.Queue = asyncio.Queue()
        self._pusher: Optional[asyncio.Task] = None

    # ------------------------------------------------------ lifecycle

    async def start(self) -> None:
        await self.client.subscribe(MSG_PREFIX + self.local_cluster, qos=1)
        # every (re)connect pushes a full resync: the remote may have
        # restarted with an empty extern-route table, and a silent gap
        # would permanently stop forwarding
        self.client.on_connect = lambda: self._ops.put_nowait(
            ("reset", None)
        )
        await self.client.start()
        self._pusher = asyncio.get_running_loop().create_task(
            self._push_loop()
        )

    async def stop(self) -> None:
        if self._pusher is not None:
            # a push's PUBACK resolving exactly as stop() cancels used
            # to swallow the cancellation (bpo-37658) and hang the
            # whole broker shutdown on this await — hence the re-
            # cancelling helper
            await cancel_and_wait(self._pusher)
            self._pusher = None
        await self.client.stop()

    # ----------------------------------------------------- route sync

    def relevant(self, flt: str) -> bool:
        return any(filters_intersect(flt, t) for t in self.topics)

    def route_added(self, flt: str) -> None:
        if not flt.startswith("$") and self.relevant(flt):
            self._ops.put_nowait(("add", flt))

    def route_removed(self, flt: str) -> None:
        if not flt.startswith("$") and self.relevant(flt):
            self._ops.put_nowait(("del", flt))

    def _current_filters(self) -> List[str]:
        router = self.broker.router
        out = set()
        for flt in list(router._subs) + list(router._shared_opts):
            if not flt.startswith("$") and self.relevant(flt):
                out.add(flt)
        return sorted(out)

    async def _push_loop(self) -> None:
        """Serialize route ops onto the link connection; a reconnect
        collapses the queue into one reset (full resync)."""
        topic = ROUTE_PREFIX + self.local_cluster
        while True:
            op, flt = await self._ops.get()
            try:
                if op == "reset":
                    await self.client.connected.wait()
                    filters = self._current_filters()
                    self._pushed = set(filters)
                    body = {"op": "reset", "filters": filters}
                else:
                    if (op == "add") == (flt in self._pushed):
                        continue  # dedup repeated adds/dels
                    await self.client.connected.wait()
                    (self._pushed.add if op == "add"
                     else self._pushed.discard)(flt)
                    body = {"op": op, "filters": [flt]}
                await self.client.publish(
                    topic, json.dumps(body).encode(), qos=1
                )
            except (ConnectionError, asyncio.TimeoutError):
                # link dropped mid-push: full resync once it's back
                while not self._ops.empty():
                    self._ops.get_nowait()
                self._ops.put_nowait(("reset", None))
                await asyncio.sleep(0.2)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("cluster link %s: route push failed",
                              self.name)

    # -------------------------------------------------- message import

    def _on_remote(self, msg: Message) -> None:
        inner = _unwrap(msg.payload)
        if inner is None:
            log.warning("cluster link %s: malformed wrapped message",
                        self.name)
            return
        if inner.headers.get("cluster_origin") == self.local_cluster:
            return  # never re-import our own traffic
        if inner.topic.startswith("$"):
            # imported traffic is data, never control: a peer must not
            # inject $LINK route ops, $SYS lines, or $delayed commands
            log.warning("cluster link %s: imported message on reserved "
                        "topic %r dropped", self.name, inner.topic)
            return
        self.broker.metrics.inc("cluster_link.ingress")
        self.broker.publish(inner)


class LinkServer:
    """Remote-interest table + forwarder (the reference's extrouter +
    external-broker forward hook).

    Trust model: the $LINK control/data surface is bound to the link
    agent's SESSION identity (clientid ``$link:<peer>:...``). Clientid
    alone is spoofable on a wide-open broker — same as the reference,
    deployments must require credentials for ``$link:*`` clientids via
    the authn chain (the reference ships mandatory link ACLs for the
    same reason); a spoofer also cannot hide, since taking the agent's
    clientid kicks the live agent session."""

    def __init__(self, broker, local_cluster: str,
                 allowed: Optional[Set[str]] = None) -> None:
        self.broker = broker
        self.local_cluster = local_cluster
        # route ops are only honored for known peer clusters — without
        # this gate ANY client could push {"op":"reset","filters":["#"]}
        # under a cluster name of its choosing and siphon every publish
        # past per-topic ACLs via its own $LINK/msg subscription
        self.allowed: Set[str] = set(allowed or ())
        # remote cluster -> filters it currently wants
        self.extern_routes: Dict[str, Set[str]] = {}
        self._hook = None
        self._sub_hook = None

    def start(self) -> None:
        self._hook = self.broker.hooks.add(
            "message.publish", self._on_publish, priority=-60
        )
        self._sub_hook = self.broker.hooks.add(
            "client.subscribe", self._on_subscribe, priority=-60
        )
        # delivery-time enforcement: subscriptions can come into being
        # WITHOUT passing the client.subscribe hook (durable-session
        # resume, takeover import, a subscribe during a boot window, a
        # $share group resolved at dispatch) — so the real gate is at
        # fan-out: $LINK/msg/<c> is only ever handed to c's agent
        # session, $LINK/route/* is never delivered to anyone
        self.broker.delivery_guards.append(self._delivery_guard)

    def stop(self) -> None:
        if self._hook is not None:
            self.broker.hooks.delete("message.publish", self._hook)
            self._hook = None
        if getattr(self, "_sub_hook", None) is not None:
            self.broker.hooks.delete("client.subscribe", self._sub_hook)
            self._sub_hook = None
        if self._delivery_guard in self.broker.delivery_guards:
            self.broker.delivery_guards.remove(self._delivery_guard)

    # ---------------------------------------------------------- hook

    def _delivery_guard(self, clientid: str, msg: Message) -> bool:
        t = msg.topic
        if t.startswith(MSG_PREFIX):
            # only OUR egress wrapper reaches an agent — the header is
            # broker-internal state no wire client can set, so a local
            # client cannot hand-craft a wrapped payload and have it
            # delivered (it would be unwrapped and injected remotely
            # with forged topic/from_client, bypassing remote ACLs)
            if not msg.headers.get("link_egress"):
                return False
            c = t[len(MSG_PREFIX):]
            return c in self.allowed and self._is_agent(clientid, c)
        if t.startswith(ROUTE_PREFIX):
            return False  # control ops are consumed by the hook only
        return True

    def _is_agent(self, clientid: str, cluster: str) -> bool:
        """True when `clientid` is cluster's link agent: agents connect
        as ``$link:{their cluster}:{their name for us}`` (LinkAgent
        __init__); the ':'-delimited first field is the peer identity
        we bind to — unambiguous because ':' cannot appear in a
        cluster name."""
        if ":" in cluster:
            return False
        return clientid.startswith(f"$link:{cluster}:")

    def _on_subscribe(self, client, flt: str, opts):
        """$LINK/msg/<c> carries wrapped copies of every matching
        publish and $LINK/route/<c> is the control surface — both are
        reserved for the link agent of cluster <c>; any other
        subscription that could observe them is denied (the reference
        mandates the same via its link ACLs, emqx_cluster_link.erl
        actor authz).

        Only filters whose FIRST level is the literal ``$LINK`` can
        ever match these topics ([MQTT-4.7.2-1]: topics beginning with
        `$` never match a root wildcard), so plain ``#``/``+/...``
        subscriptions pass untouched. Shared subscriptions are checked
        on their REAL filter — ``$share/g/$LINK/msg/x`` is the same
        siphon with a prefix on it."""
        from .hooks import STOP_WITH
        try:
            share = T.parse_share(flt)
        except ValueError:
            return None  # malformed $share: channel rejects it anyway
        real = share.topic if share else flt
        if not real.startswith("$LINK/"):
            return None  # not a $LINK topic: leave the accumulator alone
        if real.startswith(MSG_PREFIX) and share is None:
            c = real[len(MSG_PREFIX):]
            if c in self.allowed and self._is_agent(client.clientid, c):
                return opts
        return STOP_WITH(None)  # deny (run_fold None => 0x87)

    def _on_publish(self, msg: Message):
        topic = msg.topic
        if topic.startswith(ROUTE_PREFIX):
            if msg.headers.get("cluster_origin"):
                # a wrapped message a peer smuggled in with a
                # $LINK/route topic: control ops are only honored from
                # directly-connected agent sessions, never from
                # imported traffic (peer B must not be able to forge
                # route ops for peer C)
                log.warning("cluster link: imported message targeting "
                            "control topic %r dropped", topic)
                return None
            self._route_op(topic[len(ROUTE_PREFIX):], msg.payload,
                           msg.from_client)
            return None
        if topic.startswith(MSG_PREFIX):
            from .hooks import STOP_WITH
            if not msg.headers.get("link_egress"):
                # a client hand-publishing a forged wrapped payload on
                # the egress topic: drop it outright (the delivery
                # guard would refuse it anyway; dropping here also
                # stops retain/persistence side effects)
                log.warning("cluster link: foreign publish on egress "
                            "topic %r from %r dropped", topic,
                            msg.from_client)
                return STOP_WITH(None)
            return None
        if topic.startswith("$"):  # $SYS, $delayed, ... never forward
            return None
        if msg.headers.get("cluster_origin"):
            # link-imported message: deliver locally only, never
            # re-export ("no gossip forwarding",
            # emqx_cluster_link.erl:86-89 forward/1 drops any message
            # carrying a link origin) — in a >=3-cluster mesh
            # re-forwarding duplicates deliveries, and in a cycle it
            # ping-pongs forever
            return None
        lifecycle = getattr(self.broker, "lifecycle", None)
        ctx = getattr(msg, "_trace_ctx", None) if (
            lifecycle is not None and lifecycle.active
        ) else None
        for cluster, filters in self.extern_routes.items():
            if any(T.match(topic, f) for f in filters):
                pend = None
                trace = None
                if ctx is not None:
                    # a sampled message's link hop gets its own span;
                    # the wrapper carries (trace, span) so the
                    # importing cluster parents to it.  Closed on
                    # EVERY outcome below — a failpoint-eaten egress
                    # still closes the publisher-side trace.
                    from .tracecontext import encode_ctx

                    pend = lifecycle.begin_forward(
                        ctx, "link.forward", cluster,
                        topic=msg.topic, mid=msg.mid.hex(),
                    )
                    trace = encode_ctx(ctx.trace_id, pend.span_id)
                if failpoints.enabled:
                    # link-forward chaos seam, keyed by peer cluster so
                    # a `match` filter partitions one link.  `drop`
                    # loses the forward silently (the remote never
                    # sees it); `error` raises into the publish hook's
                    # recovery.  Sync seam on the loop thread — inject
                    # latency at cluster.transport.* instead of here
                    try:
                        act = failpoints.evaluate(
                            "cluster.link.forward", key=cluster
                        )
                    except Exception:
                        if pend is not None:
                            pend.end(False, "failpoint error")
                        raise
                    if act == "drop":
                        if pend is not None:
                            pend.end(False, "failpoint drop")
                        continue
                self.broker.metrics.inc("cluster_link.egress")
                self.broker.publish(Message(
                    topic=MSG_PREFIX + cluster,
                    payload=_wrap(msg, self.local_cluster, trace=trace),
                    qos=1,
                    headers={"link_egress": True},
                ))
                if pend is not None:
                    pend.end(True)
        return None

    def _route_op(self, cluster: str, payload: bytes,
                  from_client: str) -> None:
        if cluster not in self.allowed:
            log.warning("cluster link: route op for unconfigured peer "
                        "%r ignored", cluster)
            return
        if not self._is_agent(from_client, cluster):
            # bind the control surface to the link agent's session —
            # otherwise any local client that can publish could reset
            # the peer's route table or inject {"op":"reset",
            # "filters":["#"]} to siphon every publish past topic ACLs
            log.warning("cluster link: route op for %r from foreign "
                        "client %r ignored", cluster, from_client)
            return
        try:
            body = json.loads(payload)
            op = body["op"]
            filters = [str(f) for f in body.get("filters", [])]
        except (ValueError, KeyError, TypeError):
            log.warning("cluster link: malformed route op from %r", cluster)
            return
        routes = self.extern_routes.setdefault(cluster, set())
        if op == "reset":
            routes.clear()
            routes.update(filters)
        elif op == "add":
            routes.update(filters)
        elif op == "del":
            routes.difference_update(filters)
        log.debug("cluster link: %s now wants %d filters",
                  cluster, len(routes))


class ClusterLinks:
    """All configured links of one broker + the serving half."""

    def __init__(self, broker, local_cluster: str,
                 links: Sequence[dict]) -> None:
        self.broker = broker
        # configured link names are the peers whose route ops we honor;
        # an `accept_from` entry extends the set for asymmetric setups
        allowed = {l["name"] for l in links}
        for l in links:
            allowed.update(l.get("accept_from", ()))
        # ':' delimits the agent identity fields ($link:{cluster}:{name});
        # a name containing it would make the identity checks fail open
        # into a silently dead link — reject at configuration time
        for n in allowed | {local_cluster}:
            if ":" in n:
                raise ValueError(
                    f"cluster name {n!r} may not contain ':' "
                    "(reserved as the link-identity separator)"
                )
        self.server = LinkServer(broker, local_cluster, allowed)
        self.agents = [
            LinkAgent(
                broker,
                local_cluster,
                name=l["name"],
                host=l.get("host", "127.0.0.1"),
                port=int(l.get("port", 1883)),
                topics=l.get("topics", ["#"]),
                username=l.get("username"),
                password=(l["password"].encode()
                          if l.get("password") else None),
            )
            for l in links
        ]
        self._prev_added = None
        self._prev_removed = None
        self._installed = False
        self._hooks_chained = False

    def install(self) -> None:
        """Register the LinkServer hooks (forwarding + the $LINK
        guard). Called by BrokerServer BEFORE listeners accept clients
        so no subscription can slip in ahead of the guard; start()
        installs lazily for embedded/test use."""
        if not self._installed:
            self.server.start()
            self._installed = True

    async def start(self) -> None:
        self.install()
        router = self.broker.router
        # chain (don't clobber) the cluster node's route hooks
        self._prev_added = router.on_route_added
        self._prev_removed = router.on_route_removed
        self._hooks_chained = True

        def added(flt, _prev=self._prev_added):
            if _prev is not None:
                _prev(flt)
            for a in self.agents:
                a.route_added(flt)

        def removed(flt, _prev=self._prev_removed):
            if _prev is not None:
                _prev(flt)
            for a in self.agents:
                a.route_removed(flt)

        router.on_route_added = added
        router.on_route_removed = removed
        for a in self.agents:
            await a.start()

    async def stop(self) -> None:
        for a in self.agents:
            await a.stop()
        self.server.stop()
        self._installed = False
        if self._hooks_chained:
            # only restore what start() actually saved — stop() after a
            # bare install() (e.g. a boot that failed between install
            # and start) must not reset the router hooks to our
            # __init__ defaults and silently cut route sync
            self.broker.router.on_route_added = self._prev_added
            self.broker.router.on_route_removed = self._prev_removed
            self._hooks_chained = False

    def info(self) -> dict:
        return {
            "links": [
                {
                    "name": a.name,
                    "topics": a.topics,
                    "connected": a.client.connected.is_set(),
                    "pushed_routes": len(a._pushed),
                }
                for a in self.agents
            ],
            "extern_routes": {
                c: sorted(f) for c, f in self.server.extern_routes.items()
            },
        }
