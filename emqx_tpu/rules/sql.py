"""Rule SQL parser: SELECT ... FROM "topic", ... [WHERE ...].

Covers the core of the reference's rule SQL (parsed there by the
`rulesql` dep behind `emqx_rule_sqlparser`, /root/reference/apps/
emqx_rule_engine/src/emqx_rule_sqlparser.erl): select lists with
aliases and nested field paths (``payload.x.y``), arithmetic,
comparison and boolean operators, function calls, IN lists, and
CASE/WHEN.  FOREACH/DO/INCASE (array unrolling) is not implemented.

Hand-written tokenizer + Pratt parser producing a plain-tuple AST:

  ("lit", value)
  ("var", ("payload", "x"))          field path
  ("call", name, [args])
  ("op", symbol, lhs, rhs)           binary
  ("neg", expr) / ("not", expr)
  ("in", expr, [exprs])
  ("case", [(when, then), ...], else_or_None)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class SqlError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<dq>"(?:[^"\\]|\\.)*")
  | (?P<sq>'(?:[^'\\]|\\.)*')
  | (?P<op><>|!=|>=|<=|=|>|<|\+|-|\*|/|\(|\)|,|\.)
  | (?P<word>[A-Za-z_$][A-Za-z0-9_$]*)
""",
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "as", "and", "or", "not", "in",
    "case", "when", "then", "else", "end", "div", "mod", "true",
    "false", "null", "like",
}


@dataclass
class Token:
    kind: str  # num | str | topic | op | word | kw | end
    value: object
    pos: int


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlError(f"bad character at {pos}: {sql[pos:pos+10]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        if m.lastgroup == "num":
            text = m.group()
            out.append(
                Token("num", float(text) if "." in text else int(text), m.start())
            )
        elif m.lastgroup == "dq":
            # double quotes delimit topics in FROM, or quoted identifiers
            out.append(
                Token("topic", _unescape(m.group()[1:-1]), m.start())
            )
        elif m.lastgroup == "sq":
            out.append(Token("str", _unescape(m.group()[1:-1]), m.start()))
        elif m.lastgroup == "op":
            out.append(Token("op", m.group(), m.start()))
        else:
            word = m.group()
            low = word.lower()
            if low in _KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("word", word, m.start()))
    out.append(Token("end", None, len(sql)))
    return out


def _unescape(s: str) -> str:
    return s.replace('\\"', '"').replace("\\'", "'").replace("\\\\", "\\")


@dataclass
class SelectField:
    expr: tuple
    alias: Optional[str] = None
    star: bool = False


@dataclass
class ParsedSql:
    fields: List[SelectField]
    froms: List[str]
    where: Optional[tuple] = None


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_kw(self, kw: str) -> None:
        t = self.next()
        if t.kind != "kw" or t.value != kw:
            raise SqlError(f"expected {kw.upper()} at {t.pos}, got {t.value!r}")

    def accept_op(self, sym: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == sym:
            self.i += 1
            return True
        return False

    def accept_kw(self, kw: str) -> bool:
        t = self.peek()
        if t.kind == "kw" and t.value == kw:
            self.i += 1
            return True
        return False

    # ---------------------------------------------------- statement

    def statement(self) -> ParsedSql:
        self.expect_kw("select")
        fields = [self.select_field()]
        while self.accept_op(","):
            fields.append(self.select_field())
        self.expect_kw("from")
        froms = [self.topic()]
        while self.accept_op(","):
            froms.append(self.topic())
        where = None
        if self.accept_kw("where"):
            where = self.expr()
        t = self.peek()
        if t.kind != "end":
            raise SqlError(f"trailing input at {t.pos}: {t.value!r}")
        return ParsedSql(fields=fields, froms=froms, where=where)

    def select_field(self) -> SelectField:
        if self.accept_op("*"):
            return SelectField(expr=("lit", None), star=True)
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            t = self.next()
            if t.kind not in ("word", "topic"):
                raise SqlError(f"bad alias at {t.pos}")
            alias = str(t.value)
        return SelectField(expr=e, alias=alias)

    def topic(self) -> str:
        t = self.next()
        if t.kind == "topic" or t.kind == "str":
            return str(t.value)
        raise SqlError(f'expected "topic" at {t.pos}')

    # -------------------------------------------------- expressions

    # precedence climbing: or < and < not < cmp < add < mul < unary
    def expr(self) -> tuple:
        return self.or_expr()

    def or_expr(self) -> tuple:
        lhs = self.and_expr()
        while self.accept_kw("or"):
            lhs = ("op", "or", lhs, self.and_expr())
        return lhs

    def and_expr(self) -> tuple:
        lhs = self.not_expr()
        while self.accept_kw("and"):
            lhs = ("op", "and", lhs, self.not_expr())
        return lhs

    def not_expr(self) -> tuple:
        if self.accept_kw("not"):
            return ("not", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self) -> tuple:
        lhs = self.add_expr()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", ">", "<", ">=", "<="):
            self.i += 1
            sym = "!=" if t.value == "<>" else str(t.value)
            return ("op", sym, lhs, self.add_expr())
        if t.kind == "kw" and t.value == "in":
            self.i += 1
            if not self.accept_op("("):
                raise SqlError(f"expected ( after IN at {self.peek().pos}")
            items = [self.expr()]
            while self.accept_op(","):
                items.append(self.expr())
            if not self.accept_op(")"):
                raise SqlError("unclosed IN list")
            return ("in", lhs, items)
        if t.kind == "kw" and t.value == "like":
            self.i += 1
            pat = self.add_expr()
            return ("call", "like", [lhs, pat])
        return lhs

    def add_expr(self) -> tuple:
        lhs = self.mul_expr()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.i += 1
                lhs = ("op", str(t.value), lhs, self.mul_expr())
            else:
                return lhs

    def mul_expr(self) -> tuple:
        lhs = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/"):
                self.i += 1
                lhs = ("op", str(t.value), lhs, self.unary())
            elif t.kind == "kw" and t.value in ("div", "mod"):
                self.i += 1
                lhs = ("op", str(t.value), lhs, self.unary())
            else:
                return lhs

    def unary(self) -> tuple:
        if self.accept_op("-"):
            return ("neg", self.unary())
        return self.primary()

    def primary(self) -> tuple:
        t = self.next()
        if t.kind == "num" or t.kind == "str":
            return ("lit", t.value)
        if t.kind == "kw":
            if t.value == "true":
                return ("lit", True)
            if t.value == "false":
                return ("lit", False)
            if t.value == "null":
                return ("lit", None)
            if t.value == "case":
                return self.case_expr()
            raise SqlError(f"unexpected keyword {t.value!r} at {t.pos}")
        if t.kind == "op" and t.value == "(":
            e = self.expr()
            if not self.accept_op(")"):
                raise SqlError("unclosed (")
            return e
        if t.kind in ("word", "topic"):
            name = str(t.value)
            if self.accept_op("("):
                args: List[tuple] = []
                if not self.accept_op(")"):
                    args.append(self.expr())
                    while self.accept_op(","):
                        args.append(self.expr())
                    if not self.accept_op(")"):
                        raise SqlError("unclosed call")
                return ("call", name.lower(), args)
            path = [name]
            while self.accept_op("."):
                nt = self.next()
                if nt.kind not in ("word", "topic", "kw"):
                    raise SqlError(f"bad field path at {nt.pos}")
                path.append(str(nt.value))
            return ("var", tuple(path))
        raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

    def case_expr(self) -> tuple:
        whens: List[Tuple[tuple, tuple]] = []
        els: Optional[tuple] = None
        while True:
            if self.accept_kw("when"):
                cond = self.expr()
                self.expect_kw("then")
                whens.append((cond, self.expr()))
            elif self.accept_kw("else"):
                els = self.expr()
            elif self.accept_kw("end"):
                if not whens:
                    raise SqlError("CASE without WHEN")
                return ("case", whens, els)
            else:
                t = self.peek()
                raise SqlError(f"bad CASE at {t.pos}: {t.value!r}")


def parse_sql(sql: str) -> ParsedSql:
    return _Parser(tokenize(sql)).statement()
