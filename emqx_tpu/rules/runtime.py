"""Rule evaluation: message -> env -> WHERE -> SELECT.

The interpreter half of the rule engine, mirroring
`emqx_rule_runtime:apply_rule` (/root/reference/apps/emqx_rule_engine/
src/emqx_rule_runtime.erl:60-100): build the event env from the
message (`emqx_rule_events:eventmsg_publish`), evaluate WHERE (any
evaluation error => no match), then evaluate the SELECT list into the
action payload.  Also the correctness oracle for the batched predicate
compiler (`predicate.py`).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional, Tuple

from ..message import Message
from .funcs import FUNCS
from .sql import ParsedSql, SelectField


class EvalError(Exception):
    pass


def build_env(msg: Message, node: str = "emqx_tpu@local") -> Dict[str, Any]:
    """The '$events/message_publish' env (emqx_rule_events.erl
    eventmsg_publish): flat columns + lazily-decoded payload.  Built
    field-by-field from `_env_field` — the same single source of
    truth `LazyEnv` materializes from on demand."""
    return {k: _env_field(msg, k, node) for k in _ENV_KEYS}


class _PayloadStr(str):
    """Payload behaves as its UTF-8 string; nested access JSON-decodes
    once and caches (the reference decodes on first payload.x use)."""

    def __new__(cls, raw: bytes):
        s = super().__new__(cls, raw.decode("utf-8", "replace"))
        s._raw = raw  # type: ignore[attr-defined]
        s._decoded: Optional[Any] = None  # type: ignore[attr-defined]
        return s

    def decoded(self) -> Any:
        if self._decoded is None:  # type: ignore[attr-defined]
            self._decoded = json.loads(str(self))  # type: ignore[attr-defined]
        return self._decoded  # type: ignore[attr-defined]


def _env_field(msg: Message, key: str, node: str) -> Any:
    """One `build_env` field, computed on demand (LazyEnv)."""
    if key == "event":
        return "message.publish"
    if key == "id":
        return msg.mid.hex()
    if key == "clientid":
        return msg.from_client
    if key == "username":
        return msg.from_username
    if key == "topic":
        return msg.topic
    if key == "qos":
        return msg.qos
    if key == "payload":
        return _PayloadStr(msg.payload)
    if key == "flags":
        return {"retain": msg.retain, "dup": msg.dup, "sys": msg.sys}
    if key == "retain":
        return msg.retain
    if key == "pub_props":
        return dict(msg.properties)
    if key in ("timestamp", "publish_received_at"):
        return int(msg.timestamp * 1000)
    if key == "node":
        return node
    raise KeyError(key)


_ENV_KEYS = (
    "event", "id", "clientid", "username", "topic", "qos", "payload",
    "flags", "retain", "pub_props", "timestamp",
    "publish_received_at", "node",
)
_ENV_FIELDS = frozenset(_ENV_KEYS)


class LazyEnv(dict):
    """`build_env` that materializes only the fields a predicate or
    SELECT actually touches.  A fallback rule reading one payload
    field over a wide message costs one payload decode and ONE dict
    entry, not the full 13-field env — and the decoded-JSON cache on
    the shared `payload` entry means the window's column extractor,
    fallback predicates, and SELECTs all decode each message at most
    once (`len(env)` counts materialized fields; the regression suite
    pins it)."""

    __slots__ = ("_msg", "_node")

    def __init__(self, msg: Message, node: str = "emqx_tpu@local"):
        super().__init__()
        self._msg = msg
        self._node = node

    def __missing__(self, key: str) -> Any:
        v = _env_field(self._msg, key, self._node)  # KeyError: unknown
        self[key] = v
        return v

    def __contains__(self, key: object) -> bool:
        return dict.__contains__(self, key) or key in _ENV_FIELDS

    def get(self, key: str, default: Any = None) -> Any:
        try:
            return self[key]
        except KeyError:
            return default


def lookup_var(env: Dict[str, Any], path: Tuple[str, ...]) -> Any:
    cur: Any = env
    for i, part in enumerate(path):
        if isinstance(cur, _PayloadStr) and i > 0:
            cur = cur.decoded()
        if isinstance(cur, dict):
            if part not in cur:
                return None
            cur = cur[part]
        else:
            raise EvalError(f"cannot descend into {part!r}")
    return cur


def eval_expr(expr: tuple, env: Dict[str, Any]) -> Any:
    kind = expr[0]
    if kind == "lit":
        return expr[1]
    if kind == "var":
        return lookup_var(env, expr[1])
    if kind == "neg":
        v = eval_expr(expr[1], env)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise EvalError(f"negating non-number {v!r}")
        return -v
    if kind == "not":
        return not _truthy(eval_expr(expr[1], env))
    if kind == "op":
        return _eval_op(expr[1], expr[2], expr[3], env)
    if kind == "in":
        v = eval_expr(expr[1], env)
        return any(_sql_eq(v, eval_expr(e, env)) for e in expr[2])
    if kind == "call":
        fn = FUNCS.get(expr[1])
        if fn is None:
            raise EvalError(f"unknown function {expr[1]!r}")
        args = [eval_expr(a, env) for a in expr[2]]
        try:
            return fn(*args)
        except EvalError:
            raise
        except Exception as exc:
            raise EvalError(f"{expr[1]}: {exc}") from exc
    if kind == "case":
        for cond, then in expr[1]:
            if _truthy(eval_expr(cond, env)):
                return eval_expr(then, env)
        return eval_expr(expr[2], env) if expr[2] is not None else None
    raise EvalError(f"bad expression node {kind!r}")


def _truthy(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if v is None:
        return False
    raise EvalError(f"non-boolean in boolean context: {v!r}")


def _sql_eq(a: Any, b: Any) -> bool:
    # numeric cross-type equality, but not bool==1
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if isinstance(a, _PayloadStr):
        a = str(a)
    if isinstance(b, _PayloadStr):
        b = str(b)
    return type(a) == type(b) and a == b


def _eval_op(sym: str, le: tuple, re_: tuple, env: Dict[str, Any]) -> Any:
    if sym == "and":
        return _truthy(eval_expr(le, env)) and _truthy(eval_expr(re_, env))
    if sym == "or":
        return _truthy(eval_expr(le, env)) or _truthy(eval_expr(re_, env))
    a = eval_expr(le, env)
    b = eval_expr(re_, env)
    if sym == "=":
        return _sql_eq(a, b)
    if sym == "!=":
        return not _sql_eq(a, b)
    if sym in (">", "<", ">=", "<="):
        if isinstance(a, str) and isinstance(b, str):
            pass  # string ordering allowed
        elif not (
            isinstance(a, (int, float))
            and isinstance(b, (int, float))
            and not isinstance(a, bool)
            and not isinstance(b, bool)
        ):
            raise EvalError(f"cannot compare {a!r} {sym} {b!r}")
        return {
            ">": a > b, "<": a < b, ">=": a >= b, "<=": a <= b
        }[sym]
    return arith_op(sym, a, b)


def arith_op(sym: str, a: Any, b: Any) -> Any:
    """One arithmetic step over already-evaluated operands — shared by
    the interpreter (`_eval_op`) and the batched SELECT transform's
    compiled expression closures (`select.py`), so the two lanes are
    bit-identical by construction (int-ness preservation, string
    concat '+', truncating div/mod, div-by-zero -> EvalError)."""
    if sym == "+" and isinstance(a, str) and isinstance(b, str):
        return a + b  # string concat like the reference's '+'
    if not (
        isinstance(a, (int, float))
        and isinstance(b, (int, float))
        and not isinstance(a, bool)
        and not isinstance(b, bool)
    ):
        raise EvalError(f"arithmetic on non-numbers: {a!r} {sym} {b!r}")
    if sym == "+":
        return a + b
    if sym == "-":
        return a - b
    if sym == "*":
        return a * b
    if sym == "/":
        if b == 0:
            raise EvalError("division by zero")
        return a / b
    if sym == "div":
        if b == 0:
            raise EvalError("division by zero")
        return int(a) // int(b)
    if sym == "mod":
        if b == 0:
            raise EvalError("division by zero")
        return int(a) % int(b)
    raise EvalError(f"bad operator {sym!r}")


def eval_where(where: Optional[tuple], env: Dict[str, Any]) -> bool:
    """WHERE evaluation; any error counts as no-match (the reference
    logs and skips, emqx_rule_runtime.erl apply_rule catch)."""
    if where is None:
        return True
    try:
        return _truthy(eval_expr(where, env))
    except (EvalError, TypeError, ValueError):
        return False


_STAR_FIELDS = (
    "clientid", "username", "topic", "qos", "payload", "retain",
    "timestamp", "event",
)


def eval_select(sql: ParsedSql, env: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for f in sql.fields:
        if f.star:
            for k in _STAR_FIELDS:
                v = env.get(k)
                out[k] = str(v) if isinstance(v, _PayloadStr) else v
            continue
        try:
            val = eval_expr(f.expr, env)
        except (EvalError, TypeError, ValueError):
            val = None
        name = f.alias or _default_name(f.expr)
        if isinstance(val, _PayloadStr):
            val = str(val)
        out[name] = val
    return out


def _default_name(expr: tuple) -> str:
    if expr[0] == "var":
        return expr[1][-1]
    if expr[0] == "call":
        return expr[1]
    return "expr"
