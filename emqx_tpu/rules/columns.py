"""Window column extraction for the stacked rule-matrix program.

`WindowColumns` decodes each window message ONCE into parallel numpy
planes over the union of var paths the registry's lowerable rules
reference (predicate.StackedRules.paths): a float64 numeric lane, a
per-window RANK-interned string lane, a lookup-error lane and a
presence lane per path.  `ops.match_kernel.rules_eval_host` /
`rules_eval_batch` then evaluate the whole registry against these
planes as one rules x window boolean matrix.

String interning rides one per-window dictionary (the string-dict
idiom `PredicateProgram.extract_columns` introduced), but assigns
SORTED ranks instead of first-seen ids: rank order == lexicographic
order, so the kernel's ordering comparisons cover interpreter string
ordering (`topic > clientid`) as well as equality.  The dictionary is
seeded with the registry's string-literal table, so literal operands
resolve to per-window ranks in one vectorized lookup
(``lit_ranks``).  Booleans take reserved ids OUTSIDE the orderable
rank space (-2 true / -3 false): equality-comparable, never
string-ordered — exactly the interpreter's Erlang-term semantics.

Non-scalar JSON values (dicts/lists) intern by a canonical encoding
under a NUL-prefixed namespace (NUL cannot occur in MQTT UTF-8
strings), so ``payload.a = payload.b`` over equal objects matches the
interpreter's term equality.

The per-message env dicts are `runtime.LazyEnv`: the extractor, any
per-RULE interpreter fallbacks, and the SELECT evaluation of passing
rules all share one env per message — and its `_PayloadStr` caches
the JSON decode, which is what makes "decode once per window" hold
across all three consumers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..message import Message
from .runtime import LazyEnv, _PayloadStr, lookup_var

# reserved string-lane ids: bools are equality-comparable but must
# never participate in rank (string) ordering
SID_NONE = -1
SID_TRUE = -2
SID_FALSE = -3
# non-scalar terms encode as -4 - rank: equality-comparable through
# the shared dictionary, excluded (negative) from rank ordering
SID_TERM_BASE = -4


def _canon(v: Any) -> str:
    """Canonical encoding for non-scalar JSON values such that
    encodings are equal iff Python ``==`` holds (numbers normalize
    through float, like Python's cross-type numeric equality —
    including bools, since the interpreter's container equality is
    plain ``==`` where ``True == 1``)."""
    if isinstance(v, (int, float)):  # bool is an int: True == 1
        return "n" + repr(float(v))
    if isinstance(v, str):
        return "s" + v
    if v is None:
        return "z"
    if isinstance(v, list):
        return "[" + ",".join(_canon(x) for x in v) + "]"
    if isinstance(v, dict):
        return (
            "{"
            + ",".join(f"{k}:{_canon(v[k])}" for k in sorted(v))
            + "}"
        )
    return "?" + repr(v)


class WindowColumns:
    """One window's shared column planes: ``num``/``sid``/``err``/
    ``prs`` are ``[P, W]`` over the registry's path union."""

    __slots__ = (
        "n", "paths", "num", "sid", "err", "prs", "lit_ranks",
        "envs", "n_strings", "has_nan_value", "vals",
    )

    def __init__(
        self,
        msgs: Sequence[Message],
        paths: Sequence[Tuple[str, ...]],
        lit_strings: Sequence[str],
        envs: Optional[List[Optional[LazyEnv]]] = None,
        keep_values: bool = False,
    ) -> None:
        n = len(msgs)
        n_paths = len(paths)
        self.n = n
        self.paths = tuple(paths)
        self.num = np.full((n_paths, n), np.nan, np.float64)
        self.sid = np.full((n_paths, n), SID_NONE, np.int32)
        self.err = np.zeros((n_paths, n), bool)
        self.prs = np.zeros((n_paths, n), bool)
        # ``keep_values``: also keep each cell's RAW extracted value
        # (the batched SELECT transform's input — int-ness and nested
        # objects survive, which the f64/rank planes erase).  None
        # covers both "missing" and "error" cells; the err lane
        # disambiguates where it matters (expression operands).
        self.vals: Optional[List[List[Any]]] = (
            [[None] * n for _ in range(n_paths)] if keep_values
            else None
        )
        if envs is None:
            envs = [None] * n
        self.envs = envs
        self.has_nan_value = False
        num, sid, err, prs = self.num, self.sid, self.err, self.prs
        vals = self.vals
        # (plane, msg, string, is_term) cells holding a string-interned
        # value, resolved after the scan once the window's full
        # dictionary is known
        pending: List[Tuple[int, int, str, bool]] = []
        # nested payload paths walk the decoded JSON directly (ONE
        # decode per message, shared with the lazy envs); everything
        # else goes through the generic env lookup
        pay_paths = [
            (p, paths[p][1:]) for p in range(n_paths)
            if paths[p][0] == "payload" and len(paths[p]) > 1
        ]
        gen_paths = [
            p for p in range(n_paths)
            if not (paths[p][0] == "payload" and len(paths[p]) > 1)
        ]
        _ERR = object()

        def classify(p: int, i: int, v: Any) -> None:
            if vals is not None and v is not None:
                # raw-value plane: _PayloadStr flattens to plain str
                # here, exactly eval_select's output conversion
                vals[p][i] = str(v) if type(v) is _PayloadStr else v
            if isinstance(v, bool):
                sid[p, i] = SID_TRUE if v else SID_FALSE
                prs[p, i] = True
            elif isinstance(v, (int, float)):
                if v != v:
                    # a LITERAL NaN payload value (json.loads accepts
                    # NaN) would alias the not-a-number sentinel; the
                    # caller degrades this window to the interpreter
                    self.has_nan_value = True
                num[p, i] = v
                prs[p, i] = True
            elif isinstance(v, str):
                pending.append((p, i, str(v), False))
                prs[p, i] = True
            elif v is not None:
                # non-scalar term: canonical id, equality-only
                pending.append((p, i, "\x00j" + _canon(v), True))
                prs[p, i] = True

        for i in range(n):
            env = envs[i]
            if env is None:
                env = envs[i] = LazyEnv(msgs[i])
            if pay_paths:
                try:
                    data = env["payload"].decoded()
                except Exception:
                    data = _ERR
                for p, rest in pay_paths:
                    if data is _ERR:
                        err[p, i] = True
                        continue
                    cur: Any = data
                    for part in rest:
                        if isinstance(cur, dict):
                            if part not in cur:
                                cur = None
                                break
                            cur = cur[part]
                        else:
                            err[p, i] = True
                            cur = _ERR
                            break
                    if cur is not _ERR:
                        classify(p, i, cur)
            for p in gen_paths:
                try:
                    v = lookup_var(env, paths[p])
                except Exception:
                    err[p, i] = True
                    continue
                classify(p, i, v)
        # rank interning: literals seed the dictionary so every
        # literal operand resolves even when absent from the window
        strings = set(lit_strings)
        for _, _, s, _t in pending:
            strings.add(s)
        rank = {s: r for r, s in enumerate(sorted(strings))}
        self.n_strings = len(rank)
        for p, i, s, term in pending:
            sid[p, i] = SID_TERM_BASE - rank[s] if term else rank[s]
        self.lit_ranks = np.fromiter(
            (rank[s] for s in lit_strings), np.int32, len(lit_strings)
        )

    def env(self, i: int) -> LazyEnv:
        """The shared lazy env for message ``i`` (fallback predicates
        and SELECT evaluation ride the same decode cache)."""
        return self.envs[i]

    def f32_safe(self, n_paths: Optional[int] = None) -> bool:
        """True when every numeric cell round-trips float32 — the
        device kernel computes in f32 (TPU-native), so a window
        carrying f32-unsafe values (millisecond timestamps are the
        canonical offender) stays on the float64 host twin, exactly
        the `PredicateProgram._f32_safe` rule.

        ``n_paths`` limits the scan to the first N path planes: the
        WHERE stack's planes are a PREFIX of the combined WHERE+SELECT
        path union, and SELECT-only planes (consumed by the float64
        numpy materialization, never by the device kernel) must not
        veto the device path — `SELECT timestamp` would otherwise pin
        every window to host."""
        a = self.num if n_paths is None else self.num[:n_paths]
        finite = a[np.isfinite(a)]
        if finite.size == 0:
            return True
        return bool((finite == finite.astype(np.float32)).all())
