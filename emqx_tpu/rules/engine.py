"""Rule registry + execution, wired into the broker's match step.

Mirrors `emqx_rule_engine` (/root/reference/apps/emqx_rule_engine/src/
emqx_rule_engine.erl): each rule's FROM filters register in the topic
index (:536 `emqx_topic_index:insert` into ?RULE_TOPIC_INDEX) and
per-message lookup is a match over that index (:226-231
`get_rules_for_topic`).  Here the rule filters go into the *same*
MatchEngine as subscriptions under a distinct fid class
``("rule", rule_id, i)``, so one batched device step returns routes
and rule hits together; `Broker._dispatch` splits the classes.

Actions mirror the reference's builtins (emqx_rule_actions): republish
(with ${var} placeholder templates, `emqx_placeholder` semantics),
console, and arbitrary Python callables (the hook for
resource/bridge-style sinks).

Execution is window-at-a-time: `apply_batch` decodes the dispatch
window ONCE into shared column planes and evaluates every lowerable
rule's WHERE as one rules x window boolean matrix (host numpy twin or
the fused device kernel in ops/match_kernel.py, per the match
engine's cost EWMAs) — the PAPER.md blueprint's "rule engine's SQL
predicates compiled into the same batched kernel".  Non-lowerable
predicates degrade per RULE to the interpreter over the same lazily
materialized envs, never pushing the window off the matrix path.
"""

from __future__ import annotations

import logging
import os
import re as _re
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..message import Message
from .columns import WindowColumns
from .predicate import (
    PredicateProgram, StackedRules, build_stack, compile_where,
)
from .runtime import LazyEnv, build_env, eval_select, eval_where
from .sql import ParsedSql, parse_sql

log = logging.getLogger("emqx_tpu.rules")

RULE_FID = "rule"  # fid class tag

# republish chains are legal but must terminate (the reference relies
# on operator care; we hard-cap recursion)
MAX_REPUBLISH_DEPTH = 8

_PLACEHOLDER = _re.compile(r"\$\{([^}]+)\}")


def render_template(template: str, data: Dict[str, Any]) -> str:
    """${a.b} placeholder substitution (emqx_placeholder parity)."""

    def sub(m):
        cur: Any = data
        for part in m.group(1).split("."):
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                return "undefined"
        if isinstance(cur, bool):
            return "true" if cur else "false"
        if isinstance(cur, bytes):
            return cur.decode("utf-8", "replace")
        if isinstance(cur, float) and cur.is_integer():
            return str(int(cur))
        if isinstance(cur, (dict, list)):
            import json

            return json.dumps(cur)
        return str(cur)

    return _PLACEHOLDER.sub(sub, template)


@dataclass
class RepublishAction:
    topic: str  # template
    payload: str = "${payload}"  # template
    qos: int = 0
    retain: bool = False

    kind: str = "republish"


@dataclass
class ConsoleAction:
    kind: str = "console"


@dataclass
class FunctionAction:
    fn: Callable[[Dict[str, Any], Message], None]
    kind: str = "function"


@dataclass
class SinkAction:
    """Forward the rule output to a registered resource's buffer worker
    (the bridge/action path: emqx_resource buffered IO).  The payload
    template renders against the SELECTed columns; None sends them as
    JSON."""

    resource_id: str
    payload: Optional[str] = None  # template; None => selected as JSON
    kind: str = "sink"


@dataclass
class AggregateAction:
    """Push the SELECTed columns into an Aggregator (the
    emqx_connector_aggregator path: records batch into time-bucketed
    CSV/JSONL objects and flush to the aggregator's delivery sink)."""

    aggregator: Any  # emqx_tpu.aggregator.Aggregator
    kind: str = "aggregate"


Action = Any


@dataclass
class Rule:
    rule_id: str
    sql: str
    parsed: ParsedSql
    actions: List[Action] = field(default_factory=list)
    enabled: bool = True
    description: str = ""
    # compiled WHERE column program (None when the AST has nodes the
    # compiler doesn't cover → per-message interpreter fallback)
    program: Optional[PredicateProgram] = None
    # counters (emqx_rule_metrics)
    matched: int = 0
    passed: int = 0
    failed: int = 0
    actions_success: int = 0
    actions_failed: int = 0

    def metrics(self) -> Dict[str, int]:
        return {
            "matched": self.matched,
            "passed": self.passed,
            "failed": self.failed,
            "actions.success": self.actions_success,
            "actions.failed": self.actions_failed,
        }


class RuleEngine:
    def __init__(self, broker=None) -> None:
        self.broker = broker
        self.rules: Dict[str, Rule] = {}
        # registry mutation counter: the stacked matrix program and
        # the engine's device program-array cache both key on it, so
        # add/remove/enable churn invalidates them coherently
        self.rules_rev = 0
        self._stack_cache: Optional[Tuple[int, StackedRules]] = None
        # "scalar" pins the per-rule interpreter referee (the
        # property suites' oracle); None takes the matrix path with
        # host-vs-device resolved by the match engine's cost EWMAs
        self.eval_force: Optional[str] = None
        self._stats = {
            "matrix_windows": 0, "scalar_windows": 0,
            "fallback_rule_evals": 0,
        }
        cfg_on = True
        if broker is not None:
            cfg_on = getattr(
                broker.config.engine, "rules_matrix", True
            )
        self._matrix_enabled = cfg_on and (
            os.environ.get("EMQX_TPU_NO_RULES_MATRIX") != "1"
        )
        # rev-keyed flatten tables: a stable position per rule (the
        # REGISTRY enumeration order — deterministic, so action order
        # is reproducible across paths and runs), its Rule object /
        # liveness / matrix row resolved once per rev, and a cache
        # mapping each distinct raw id-list the router's expansion
        # emits to its deduped position array — same-topic messages
        # share one entry, so steady-state windows flatten with ~one
        # dict probe per MESSAGE instead of per (rule x message) pair
        self._flat_key: Optional[Tuple[int, bool]] = None
        self._pos_objs: List[Rule] = []
        self._pos_live = np.zeros(0, bool)
        self._pos_row = np.zeros(0, np.int64)
        self._pos_of: Dict[str, int] = {}
        self._ids_cache: Dict[Tuple[str, ...], np.ndarray] = {}

    # ------------------------------------------------------ registry

    def _stacked(self) -> StackedRules:
        """The enabled registry's stacked WHERE program, rebuilt only
        when ``rules_rev`` moved (registry churn invalidates)."""
        cached = self._stack_cache
        if cached is not None and cached[0] == self.rules_rev:
            return cached[1]
        stack = build_stack([
            (rid, r.parsed.where)
            for rid, r in self.rules.items()
            if r.enabled
        ])
        self._stack_cache = (self.rules_rev, stack)
        return stack

    def add_rule(
        self,
        rule_id: str,
        sql: str,
        actions: Optional[List[Action]] = None,
        enabled: bool = True,
        description: str = "",
    ) -> Rule:
        # validate fully BEFORE touching the registry/index, so a bad
        # update cannot destroy or half-register a live rule
        parsed = parse_sql(sql)
        from .. import topic as T

        for flt in parsed.froms:
            T.validate_filter(flt)
        if rule_id in self.rules:
            self.remove_rule(rule_id)
        rule = Rule(
            rule_id=rule_id,
            sql=sql,
            parsed=parsed,
            actions=list(actions or ()),
            enabled=enabled,
            description=description,
            program=compile_where(parsed.where),
        )
        self.rules[rule_id] = rule
        self.rules_rev += 1
        if self.broker is not None:
            eng = self.broker.router.engine
            for i, flt in enumerate(parsed.froms):
                eng.insert(flt, (RULE_FID, rule_id, i))
        return rule

    def remove_rule(self, rule_id: str) -> bool:
        rule = self.rules.pop(rule_id, None)
        if rule is None:
            return False
        self.rules_rev += 1
        if self.broker is not None:
            eng = self.broker.router.engine
            for i in range(len(rule.parsed.froms)):
                eng.delete((RULE_FID, rule_id, i))
        return True

    def enable_rule(self, rule_id: str, enabled: bool) -> None:
        self.rules[rule_id].enabled = enabled
        self.rules_rev += 1

    # ----------------------------------------------------- execution

    def apply(self, msg: Message, rule_ids: List[str]) -> int:
        """Run the listed rules against one message; returns how many
        passed their WHERE (emqx_rule_runtime:apply_rules/3)."""
        if not rule_ids:
            return 0
        env = build_env(msg)
        hits = 0
        for rid in rule_ids:
            rule = self.rules.get(rid)
            if rule is None or not rule.enabled:
                continue
            rule.matched += 1
            if not eval_where(rule.parsed.where, env):
                rule.failed += 1
                continue
            rule.passed += 1
            hits += 1
            selected = eval_select(rule.parsed, env)
            self._run_actions(rule, selected, msg)
        if self.broker is not None and hits:
            self.broker.metrics.inc("rules.matched", hits)
        return hits

    def apply_batch(
        self, items: List[Tuple[Message, List[str]]], rec=None
    ) -> int:
        """Run rule hits for a whole dispatch window in ONE registry
        pass: the window's messages decode once into shared column
        planes (`WindowColumns`), every lowerable rule's WHERE
        evaluates as a row of the stacked rules x window boolean
        matrix (numpy host twin or the fused device kernel, chosen by
        the match engine's cost EWMAs), and only non-lowerable rules
        (regex/UDF-shaped calls, CASE) degrade — per RULE, not per
        window — to the interpreter over the SAME lazily-materialized
        envs.  Matched/passed/failed counters update once per rule
        and broker metrics flush in one `inc_bulk` pass.

        ``rec`` (the window's profiler record) takes ``rules_extract``
        / ``rules_eval`` sub-stages so the bench can attribute column
        extraction vs matrix evaluation inside the ``rules`` lap."""
        if not items:
            return 0
        msgs = [m for m, _ in items]
        n = len(msgs)
        envs: List[Optional[LazyEnv]] = [None] * n

        def env(i: int) -> LazyEnv:
            e = envs[i]
            if e is None:
                e = envs[i] = LazyEnv(msgs[i])
            return e

        # flatten the sink to (rule-position, msg) pair columns over
        # the rev-stable position space (see __init__): one flatten-
        # cache probe per message on the steady state, with dedup and
        # canonical ordering done by `np.unique` once per DISTINCT
        # raw id list
        use_matrix = (
            self._matrix_enabled and self.eval_force != "scalar"
        )
        stack: Optional[StackedRules] = None
        if use_matrix:
            stack = self._stacked()
        key = (self.rules_rev, use_matrix)
        if self._flat_key != key:
            self._flat_key = key
            objs = list(self.rules.values())
            n_all = len(objs)
            self._pos_objs = objs
            self._pos_of = {
                r.rule_id: k for k, r in enumerate(objs)
            }
            self._pos_live = np.fromiter(
                (r.enabled for r in objs), bool, n_all
            )
            row_of = stack.row_of if stack is not None else {}
            self._pos_row = np.fromiter(
                (
                    row_of.get(r.rule_id, -1) if r.enabled else -1
                    for r in objs
                ),
                np.int64, n_all,
            )
            self._ids_cache = {}
        objs = self._pos_objs
        n_pos = len(objs)
        pos_of = self._pos_of
        cache = self._ids_cache
        parts: List[np.ndarray] = []
        lens: List[int] = []
        for _, rids in items:
            ck = tuple(rids)
            arr = cache.get(ck)
            if arr is None:
                if len(cache) > 4096:
                    cache.clear()
                arr = cache[ck] = np.unique(np.fromiter(
                    (
                        pos_of[r] for r in rids if r in pos_of
                    ),
                    np.int64,
                ))
            parts.append(arr)
            lens.append(arr.size)
        ppos = (
            np.concatenate(parts) if parts
            else np.zeros(0, np.int64)
        )
        pmsg = np.repeat(np.arange(n, dtype=np.int64), lens)
        plive = self._pos_live[ppos]
        prow = self._pos_row[ppos]
        matrix = None
        if use_matrix:
            known = prow >= 0
            active = np.unique(prow[known])
            if active.size:
                t0 = time.perf_counter()
                cols = WindowColumns(
                    msgs, stack.paths, stack.lit_strings, envs
                )
                t1 = time.perf_counter()
                if cols.has_nan_value:
                    # a literal NaN payload value aliases the num
                    # lane's not-a-number sentinel: this window's
                    # rules take the interpreter (bit-exactness over
                    # speed for a pathological payload)
                    pass
                elif self.broker is not None:
                    matrix, _path = (
                        self.broker.router.engine.rules_eval_window(
                            stack, self.rules_rev, cols, rows=active
                        )
                    )
                else:  # standalone engines: the host twin directly
                    from ..ops.match_kernel import rules_eval_host

                    sub = rules_eval_host(
                        stack.code[active], stack.a0[active],
                        stack.a1[active], stack.a2[active],
                        stack.a3[active], stack.litn[active],
                        cols.lit_ranks, stack.last[active],
                        cols.num, cols.sid, cols.err, cols.prs,
                    )
                    matrix = np.zeros(
                        (stack.n_rules, cols.n), bool
                    )
                    matrix[active] = sub
                if matrix is not None:
                    self._stats["matrix_windows"] += 1
                    if rec is not None:
                        t2 = time.perf_counter()
                        rec.sub("rules_extract", t1 - t0)
                        rec.sub("rules_eval", t2 - t1)
        if matrix is None:
            self._stats["scalar_windows"] += 1
            known = np.zeros(len(ppos), bool)
        passmask = np.zeros(len(ppos), bool)
        if matrix is not None:
            passmask[known] = matrix[prow[known], pmsg[known]]
        # per-RULE interpreter fallback riding the shared lazy envs
        # (one JSON decode per message, window-wide)
        fb = np.nonzero(plive & ~known)[0]
        if fb.size:
            self._stats["fallback_rule_evals"] += int(fb.size)
            ppos_l = ppos.tolist()
            pmsg_l = pmsg.tolist()
            for j in fb.tolist():
                rule = objs[ppos_l[j]]
                passmask[j] = eval_where(
                    rule.parsed.where, env(pmsg_l[j])
                )
        passmask &= plive
        # matched/passed/failed flush: ONE bincount pass over the
        # pair columns, one += per rule TOUCHED this window
        m_cnt = np.bincount(ppos[plive], minlength=n_pos)
        p_cnt = np.bincount(ppos[passmask], minlength=n_pos)
        touched = np.nonzero(m_cnt)[0]
        for pos, mc, pc in zip(
            touched.tolist(),
            m_cnt[touched].tolist(),
            p_cnt[touched].tolist(),
        ):
            rule = objs[pos]
            rule.matched += mc
            rule.passed += pc
            rule.failed += mc - pc
        hits = int(passmask.sum())
        mloc: Counter = Counter()  # one inc_bulk flush per window
        sel = np.nonzero(passmask)[0]
        if sel.size:
            # canonical action order: rule-major in REGISTRY order,
            # message index ascending within a rule — identical
            # across the device / host / scalar-referee paths
            order = np.lexsort((pmsg[sel], ppos[sel]))
            sel_l = sel[order].tolist()
            ppos_l = ppos.tolist()
            pmsg_l = pmsg.tolist()
            for j in sel_l:
                rule = objs[ppos_l[j]]
                if not rule.actions:
                    # nothing consumes the SELECT columns: skip the
                    # per-hit projection entirely (counter-only rules)
                    continue
                i = pmsg_l[j]
                selected = eval_select(rule.parsed, env(i))
                self._run_actions(rule, selected, msgs[i], mloc)
        if hits:
            mloc["rules.matched"] += hits
        if self.broker is not None and mloc:
            self.broker.metrics.inc_bulk(mloc)
        return hits

    def _run_actions(
        self,
        rule: Rule,
        selected: Dict[str, Any],
        msg: Message,
        mloc: Optional[Counter] = None,
    ) -> None:
        for action in rule.actions:
            try:
                self._run_action(action, selected, msg)
                rule.actions_success += 1
                if mloc is not None:
                    mloc["actions.success"] += 1
                elif self.broker is not None:
                    self.broker.metrics.inc("actions.success")
            except Exception as exc:
                rule.actions_failed += 1
                if mloc is not None:
                    mloc["actions.failed"] += 1
                elif self.broker is not None:
                    self.broker.metrics.inc("actions.failed")
                log.warning(
                    "rule %s action %s failed: %s",
                    rule.rule_id,
                    getattr(action, "kind", action),
                    exc,
                )

    def _run_action(
        self, action: Action, selected: Dict[str, Any], msg: Message
    ) -> None:
        if isinstance(action, RepublishAction):
            depth = int(msg.headers.get("republish_depth", 0))
            if depth >= MAX_REPUBLISH_DEPTH:
                raise RuntimeError("republish depth cap hit (rule loop?)")
            out = Message(
                topic=render_template(action.topic, selected),
                payload=render_template(action.payload, selected).encode(),
                qos=action.qos,
                retain=action.retain,
                from_client=msg.from_client,
                from_username=msg.from_username,
                headers={"republish_depth": depth + 1},
            )
            if self.broker is None:
                raise RuntimeError("republish without a broker")
            self.broker.publish(out)
        elif isinstance(action, ConsoleAction):
            log.info("rule output: %s", selected)
        elif isinstance(action, FunctionAction):
            action.fn(selected, msg)
        elif isinstance(action, AggregateAction):
            action.aggregator.push([selected])
        elif isinstance(action, SinkAction):
            if self.broker is None:
                raise RuntimeError("sink action without a broker")
            worker = self.broker.resources.get(action.resource_id)
            if worker is None:
                raise RuntimeError(
                    f"unknown resource {action.resource_id!r}"
                )
            if action.payload is not None:
                query: Any = render_template(action.payload, selected)
            else:
                import json as _json

                query = _json.dumps(selected, default=str)
            worker.enqueue(query)
        else:
            raise RuntimeError(f"unknown action {action!r}")

    def info(self) -> List[Dict[str, Any]]:
        return [
            {
                "id": r.rule_id,
                "sql": r.sql,
                "enabled": r.enabled,
                "description": r.description,
                **r.metrics(),
            }
            for r in self.rules.values()
        ]

    def stats(self) -> Dict[str, Any]:
        """The rule-eval gauge surface (`MatchEngine.stats()`-form):
        lowered-vs-fallback registry split, path window counts, the
        engine's per-cell cost EWMAs and breaker state — exposed
        through ``/metrics``, ``GET /api/v5/rules`` and $SYS."""
        stack = self._stacked()
        out: Dict[str, Any] = {
            "rules": len(self.rules),
            "lowered": stack.n_lowered,
            "program_rows": stack.n_rules,  # after program dedup
            "fallback": len(stack.fallback),
            "matrix_enabled": self._matrix_enabled,
            "matrix_windows": self._stats["matrix_windows"],
            "scalar_windows": self._stats["scalar_windows"],
            "fallback_rule_evals": self._stats["fallback_rule_evals"],
        }
        if self.broker is not None:
            eng = self.broker.router.engine
            out["host_windows"] = eng._rul_stats["host_windows"]
            out["dev_windows"] = eng._rul_stats["dev_windows"]
            out["dev_errors"] = eng._rul_stats["dev_errors"]
            out["host_us_ewma"] = eng._rul_host_us
            out["dev_us_ewma"] = eng._rul_dev_us
            out["breaker_open"] = eng.breaker_open
        return out
