"""Rule registry + execution, wired into the broker's match step.

Mirrors `emqx_rule_engine` (/root/reference/apps/emqx_rule_engine/src/
emqx_rule_engine.erl): each rule's FROM filters register in the topic
index (:536 `emqx_topic_index:insert` into ?RULE_TOPIC_INDEX) and
per-message lookup is a match over that index (:226-231
`get_rules_for_topic`).  Here the rule filters go into the *same*
MatchEngine as subscriptions under a distinct fid class
``("rule", rule_id, i)``, so one batched device step returns routes
and rule hits together; `Broker._dispatch` splits the classes.

Actions mirror the reference's builtins (emqx_rule_actions): republish
(with ${var} placeholder templates, `emqx_placeholder` semantics),
console, and arbitrary Python callables (the hook for
resource/bridge-style sinks).
"""

from __future__ import annotations

import logging
import re as _re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..message import Message
from .predicate import PredicateProgram, compile_where
from .runtime import build_env, eval_select, eval_where
from .sql import ParsedSql, parse_sql

log = logging.getLogger("emqx_tpu.rules")

RULE_FID = "rule"  # fid class tag

# republish chains are legal but must terminate (the reference relies
# on operator care; we hard-cap recursion)
MAX_REPUBLISH_DEPTH = 8

_PLACEHOLDER = _re.compile(r"\$\{([^}]+)\}")


def render_template(template: str, data: Dict[str, Any]) -> str:
    """${a.b} placeholder substitution (emqx_placeholder parity)."""

    def sub(m):
        cur: Any = data
        for part in m.group(1).split("."):
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                return "undefined"
        if isinstance(cur, bool):
            return "true" if cur else "false"
        if isinstance(cur, bytes):
            return cur.decode("utf-8", "replace")
        if isinstance(cur, float) and cur.is_integer():
            return str(int(cur))
        if isinstance(cur, (dict, list)):
            import json

            return json.dumps(cur)
        return str(cur)

    return _PLACEHOLDER.sub(sub, template)


@dataclass
class RepublishAction:
    topic: str  # template
    payload: str = "${payload}"  # template
    qos: int = 0
    retain: bool = False

    kind: str = "republish"


@dataclass
class ConsoleAction:
    kind: str = "console"


@dataclass
class FunctionAction:
    fn: Callable[[Dict[str, Any], Message], None]
    kind: str = "function"


@dataclass
class SinkAction:
    """Forward the rule output to a registered resource's buffer worker
    (the bridge/action path: emqx_resource buffered IO).  The payload
    template renders against the SELECTed columns; None sends them as
    JSON."""

    resource_id: str
    payload: Optional[str] = None  # template; None => selected as JSON
    kind: str = "sink"


@dataclass
class AggregateAction:
    """Push the SELECTed columns into an Aggregator (the
    emqx_connector_aggregator path: records batch into time-bucketed
    CSV/JSONL objects and flush to the aggregator's delivery sink)."""

    aggregator: Any  # emqx_tpu.aggregator.Aggregator
    kind: str = "aggregate"


Action = Any


@dataclass
class Rule:
    rule_id: str
    sql: str
    parsed: ParsedSql
    actions: List[Action] = field(default_factory=list)
    enabled: bool = True
    description: str = ""
    # compiled WHERE column program (None when the AST has nodes the
    # compiler doesn't cover → per-message interpreter fallback)
    program: Optional[PredicateProgram] = None
    # counters (emqx_rule_metrics)
    matched: int = 0
    passed: int = 0
    failed: int = 0
    actions_success: int = 0
    actions_failed: int = 0

    def metrics(self) -> Dict[str, int]:
        return {
            "matched": self.matched,
            "passed": self.passed,
            "failed": self.failed,
            "actions.success": self.actions_success,
            "actions.failed": self.actions_failed,
        }


class RuleEngine:
    def __init__(self, broker=None) -> None:
        self.broker = broker
        self.rules: Dict[str, Rule] = {}

    # ------------------------------------------------------ registry

    def add_rule(
        self,
        rule_id: str,
        sql: str,
        actions: Optional[List[Action]] = None,
        enabled: bool = True,
        description: str = "",
    ) -> Rule:
        # validate fully BEFORE touching the registry/index, so a bad
        # update cannot destroy or half-register a live rule
        parsed = parse_sql(sql)
        from .. import topic as T

        for flt in parsed.froms:
            T.validate_filter(flt)
        if rule_id in self.rules:
            self.remove_rule(rule_id)
        rule = Rule(
            rule_id=rule_id,
            sql=sql,
            parsed=parsed,
            actions=list(actions or ()),
            enabled=enabled,
            description=description,
            program=compile_where(parsed.where),
        )
        self.rules[rule_id] = rule
        if self.broker is not None:
            eng = self.broker.router.engine
            for i, flt in enumerate(parsed.froms):
                eng.insert(flt, (RULE_FID, rule_id, i))
        return rule

    def remove_rule(self, rule_id: str) -> bool:
        rule = self.rules.pop(rule_id, None)
        if rule is None:
            return False
        if self.broker is not None:
            eng = self.broker.router.engine
            for i in range(len(rule.parsed.froms)):
                eng.delete((RULE_FID, rule_id, i))
        return True

    def enable_rule(self, rule_id: str, enabled: bool) -> None:
        self.rules[rule_id].enabled = enabled

    # ----------------------------------------------------- execution

    def apply(self, msg: Message, rule_ids: List[str]) -> int:
        """Run the listed rules against one message; returns how many
        passed their WHERE (emqx_rule_runtime:apply_rules/3)."""
        if not rule_ids:
            return 0
        env = build_env(msg)
        hits = 0
        for rid in rule_ids:
            rule = self.rules.get(rid)
            if rule is None or not rule.enabled:
                continue
            rule.matched += 1
            if not eval_where(rule.parsed.where, env):
                rule.failed += 1
                continue
            rule.passed += 1
            hits += 1
            selected = eval_select(rule.parsed, env)
            self._run_actions(rule, selected, msg)
        if self.broker is not None and hits:
            self.broker.metrics.inc("rules.matched", hits)
        return hits

    def apply_batch(
        self, items: List[Tuple[Message, List[str]]]
    ) -> int:
        """Run rule hits for a whole publish micro-batch: per rule, the
        WHERE evaluates over all its matched messages in one vectorized
        column pass (PredicateProgram; interpreter fallback for
        uncompilable predicates) — the batched analogue of
        emqx_rule_runtime:apply_rules/3 per message."""
        if not items:
            return 0
        if len(items) == 1:
            return self.apply(items[0][0], items[0][1])
        msgs = [m for m, _ in items]
        env_cache: List[Optional[Dict[str, Any]]] = [None] * len(items)

        def env(i: int) -> Dict[str, Any]:
            e = env_cache[i]
            if e is None:
                e = env_cache[i] = build_env(msgs[i])
            return e

        by_rule: Dict[str, List[int]] = {}
        for i, (_, rids) in enumerate(items):
            for rid in rids:
                by_rule.setdefault(rid, []).append(i)
        hits = 0
        for rid, idxs in by_rule.items():
            rule = self.rules.get(rid)
            if rule is None or not rule.enabled:
                continue
            rule.matched += len(idxs)
            if rule.program is not None and len(idxs) > 1:
                mask = rule.program.eval_batch([env(i) for i in idxs])
                passed = [i for i, ok in zip(idxs, mask.tolist()) if ok]
            else:
                passed = [
                    i
                    for i in idxs
                    if eval_where(rule.parsed.where, env(i))
                ]
            rule.failed += len(idxs) - len(passed)
            rule.passed += len(passed)
            hits += len(passed)
            for i in passed:
                selected = eval_select(rule.parsed, env(i))
                self._run_actions(rule, selected, msgs[i])
        if self.broker is not None and hits:
            self.broker.metrics.inc("rules.matched", hits)
        return hits

    def _run_actions(
        self, rule: Rule, selected: Dict[str, Any], msg: Message
    ) -> None:
        for action in rule.actions:
            try:
                self._run_action(action, selected, msg)
                rule.actions_success += 1
                if self.broker is not None:
                    self.broker.metrics.inc("actions.success")
            except Exception as exc:
                rule.actions_failed += 1
                if self.broker is not None:
                    self.broker.metrics.inc("actions.failed")
                log.warning(
                    "rule %s action %s failed: %s",
                    rule.rule_id,
                    getattr(action, "kind", action),
                    exc,
                )

    def _run_action(
        self, action: Action, selected: Dict[str, Any], msg: Message
    ) -> None:
        if isinstance(action, RepublishAction):
            depth = int(msg.headers.get("republish_depth", 0))
            if depth >= MAX_REPUBLISH_DEPTH:
                raise RuntimeError("republish depth cap hit (rule loop?)")
            out = Message(
                topic=render_template(action.topic, selected),
                payload=render_template(action.payload, selected).encode(),
                qos=action.qos,
                retain=action.retain,
                from_client=msg.from_client,
                from_username=msg.from_username,
                headers={"republish_depth": depth + 1},
            )
            if self.broker is None:
                raise RuntimeError("republish without a broker")
            self.broker.publish(out)
        elif isinstance(action, ConsoleAction):
            log.info("rule output: %s", selected)
        elif isinstance(action, FunctionAction):
            action.fn(selected, msg)
        elif isinstance(action, AggregateAction):
            action.aggregator.push([selected])
        elif isinstance(action, SinkAction):
            if self.broker is None:
                raise RuntimeError("sink action without a broker")
            worker = self.broker.resources.get(action.resource_id)
            if worker is None:
                raise RuntimeError(
                    f"unknown resource {action.resource_id!r}"
                )
            if action.payload is not None:
                query: Any = render_template(action.payload, selected)
            else:
                import json as _json

                query = _json.dumps(selected, default=str)
            worker.enqueue(query)
        else:
            raise RuntimeError(f"unknown action {action!r}")

    def info(self) -> List[Dict[str, Any]]:
        return [
            {
                "id": r.rule_id,
                "sql": r.sql,
                "enabled": r.enabled,
                "description": r.description,
                **r.metrics(),
            }
            for r in self.rules.values()
        ]
