"""Rule registry + execution, wired into the broker's match step.

Mirrors `emqx_rule_engine` (/root/reference/apps/emqx_rule_engine/src/
emqx_rule_engine.erl): each rule's FROM filters register in the topic
index (:536 `emqx_topic_index:insert` into ?RULE_TOPIC_INDEX) and
per-message lookup is a match over that index (:226-231
`get_rules_for_topic`).  Here the rule filters go into the *same*
MatchEngine as subscriptions under a distinct fid class
``("rule", rule_id, i)``, so one batched device step returns routes
and rule hits together; `Broker._dispatch` splits the classes.

Actions mirror the reference's builtins (emqx_rule_actions): republish
(with ${var} placeholder templates, `emqx_placeholder` semantics),
console, and arbitrary Python callables (the hook for
resource/bridge-style sinks).

Execution is window-at-a-time: `apply_batch` decodes the dispatch
window ONCE into shared column planes and evaluates every lowerable
rule's WHERE as one rules x window boolean matrix (host numpy twin or
the fused device kernel in ops/match_kernel.py, per the match
engine's cost EWMAs) — the PAPER.md blueprint's "rule engine's SQL
predicates compiled into the same batched kernel".  Non-lowerable
predicates degrade per RULE to the interpreter over the same lazily
materialized envs, never pushing the window off the matrix path.
"""

from __future__ import annotations

import json as _json
import logging
import os
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..message import Message
from .columns import WindowColumns
from .predicate import (
    PredicateProgram, StackedRules, build_stack, compile_where,
)
from .runtime import LazyEnv, build_env, eval_select, eval_where
from .select import (
    SelectStack, build_select_stack, compile_template,
    materialize_rows,
)
from .sql import ParsedSql, parse_sql

log = logging.getLogger("emqx_tpu.rules")

RULE_FID = "rule"  # fid class tag

# republish chains are legal but must terminate (the reference relies
# on operator care; we hard-cap recursion)
MAX_REPUBLISH_DEPTH = 8


def render_template(template: str, data: Dict[str, Any]) -> str:
    """${a.b} placeholder substitution (emqx_placeholder parity),
    through the compiled segment-program cache (`select.py`) — action
    templates attached to registered rules are compiled once at
    rule-add and skip even the cache probe."""
    return compile_template(template).render(data)


@dataclass
class RepublishAction:
    topic: str  # template
    payload: str = "${payload}"  # template
    qos: int = 0
    retain: bool = False

    kind: str = "republish"


@dataclass
class ConsoleAction:
    kind: str = "console"


@dataclass
class FunctionAction:
    fn: Callable[[Dict[str, Any], Message], None]
    kind: str = "function"


@dataclass
class SinkAction:
    """Forward the rule output to a registered resource's buffer worker
    (the bridge/action path: emqx_resource buffered IO).  The payload
    template renders against the SELECTed columns; None sends them as
    JSON."""

    resource_id: str
    payload: Optional[str] = None  # template; None => selected as JSON
    kind: str = "sink"


@dataclass
class AggregateAction:
    """Push the SELECTed columns into an Aggregator (the
    emqx_connector_aggregator path: records batch into time-bucketed
    CSV/JSONL objects and flush to the aggregator's delivery sink)."""

    aggregator: Any  # emqx_tpu.aggregator.Aggregator
    kind: str = "aggregate"


Action = Any


@dataclass
class Rule:
    rule_id: str
    sql: str
    parsed: ParsedSql
    actions: List[Action] = field(default_factory=list)
    enabled: bool = True
    description: str = ""
    # compiled WHERE column program (None when the AST has nodes the
    # compiler doesn't cover → per-message interpreter fallback)
    program: Optional[PredicateProgram] = None
    # counters (emqx_rule_metrics)
    matched: int = 0
    passed: int = 0
    failed: int = 0
    actions_success: int = 0
    actions_failed: int = 0

    def metrics(self) -> Dict[str, int]:
        return {
            "matched": self.matched,
            "passed": self.passed,
            "failed": self.failed,
            "actions.success": self.actions_success,
            "actions.failed": self.actions_failed,
        }


class RuleEngine:
    def __init__(self, broker=None) -> None:
        self.broker = broker
        self.rules: Dict[str, Rule] = {}
        # registry mutation counter: the stacked matrix program and
        # the engine's device program-array cache both key on it, so
        # add/remove/enable churn invalidates them coherently
        self.rules_rev = 0
        self._stack_cache: Optional[Tuple[int, StackedRules]] = None
        # "scalar" pins the per-rule interpreter referee (the
        # property suites' oracle); None takes the matrix path with
        # host-vs-device resolved by the match engine's cost EWMAs
        self.eval_force: Optional[str] = None
        # SELECT lane pin: "scalar" keeps the interpreter referee for
        # every rule's SELECT+actions, "batched" pins the column
        # transform past the cost gate, None auto (EWMA-gated)
        self.select_force: Optional[str] = None
        self._sel_cache: Optional[Tuple[int, SelectStack]] = None
        # cost-EWMA gate state (the WHERE matrix idiom): per-row us
        # for each lane, sampled on single-lane windows only; tripping
        # the breaker pins scalar until registry churn
        self._sel_batch_off = False
        self._sel_us_b: Optional[float] = None
        self._sel_us_s: Optional[float] = None
        self._sel_n_b = 0
        self._sel_n_s = 0
        self._stats = {
            "matrix_windows": 0, "scalar_windows": 0,
            "fallback_rule_evals": 0,
            "select_batched_rows": 0, "select_scalar_rows": 0,
            "select_ewma_off": 0,
        }
        cfg_on = True
        if broker is not None:
            cfg_on = getattr(
                broker.config.engine, "rules_matrix", True
            )
        self._matrix_enabled = cfg_on and (
            os.environ.get("EMQX_TPU_NO_RULES_MATRIX") != "1"
        )
        # rev-keyed flatten tables: a stable position per rule (the
        # REGISTRY enumeration order — deterministic, so action order
        # is reproducible across paths and runs), its Rule object /
        # liveness / matrix row resolved once per rev, and a cache
        # mapping each distinct raw id-list the router's expansion
        # emits to its deduped position array — same-topic messages
        # share one entry, so steady-state windows flatten with ~one
        # dict probe per MESSAGE instead of per (rule x message) pair
        self._flat_key: Optional[Tuple[int, bool]] = None
        self._pos_objs: List[Rule] = []
        self._pos_live = np.zeros(0, bool)
        self._pos_row = np.zeros(0, np.int64)
        self._pos_of: Dict[str, int] = {}
        self._ids_cache: Dict[Tuple[str, ...], np.ndarray] = {}
        # per-position batched-egress plan: (SelectProgram, planes)
        # when the rule's SELECT lowered AND every action is window-
        # shaped (Sink/Aggregate); None degrades the rule to the
        # scalar referee loop
        self._pos_selp: List[Optional[tuple]] = []

    # ------------------------------------------------------ registry

    def _stacked(self) -> StackedRules:
        """The enabled registry's stacked WHERE program, rebuilt only
        when ``rules_rev`` moved (registry churn invalidates)."""
        cached = self._stack_cache
        if cached is not None and cached[0] == self.rules_rev:
            return cached[1]
        stack = build_stack([
            (rid, r.parsed.where)
            for rid, r in self.rules.items()
            if r.enabled
        ])
        self._stack_cache = (self.rules_rev, stack)
        return stack

    def _select_stack(self, stack: StackedRules) -> SelectStack:
        """The enabled registry's lowered SELECT programs, sharing the
        WHERE stack's path union (SELECT-only paths are APPENDED, so
        the WHERE rows' plane indices survive)."""
        cached = self._sel_cache
        if cached is not None and cached[0] == self.rules_rev:
            return cached[1]
        sel = build_select_stack(
            [
                (rid, r.parsed)
                for rid, r in self.rules.items()
                if r.enabled
            ],
            stack.paths,
        )
        self._sel_cache = (self.rules_rev, sel)
        return sel

    def add_rule(
        self,
        rule_id: str,
        sql: str,
        actions: Optional[List[Action]] = None,
        enabled: bool = True,
        description: str = "",
    ) -> Rule:
        # validate fully BEFORE touching the registry/index, so a bad
        # update cannot destroy or half-register a live rule
        parsed = parse_sql(sql)
        from .. import topic as T

        for flt in parsed.froms:
            T.validate_filter(flt)
        if rule_id in self.rules:
            self.remove_rule(rule_id)
        rule = Rule(
            rule_id=rule_id,
            sql=sql,
            parsed=parsed,
            actions=list(actions or ()),
            enabled=enabled,
            description=description,
            program=compile_where(parsed.where),
        )
        # precompile every action template ONCE at rule-add (the old
        # render_template re-walked the regex per message); both the
        # batched transform and the scalar referee render through the
        # attached programs
        for a in rule.actions:
            if isinstance(a, RepublishAction):
                a._topic_prog = compile_template(a.topic)
                a._payload_prog = compile_template(a.payload)
            elif isinstance(a, SinkAction) and a.payload is not None:
                a._payload_prog = compile_template(a.payload)
        self.rules[rule_id] = rule
        self.rules_rev += 1
        if self.broker is not None:
            eng = self.broker.router.engine
            for i, flt in enumerate(parsed.froms):
                eng.insert(flt, (RULE_FID, rule_id, i))
        return rule

    def remove_rule(self, rule_id: str) -> bool:
        rule = self.rules.pop(rule_id, None)
        if rule is None:
            return False
        self.rules_rev += 1
        if self.broker is not None:
            eng = self.broker.router.engine
            for i in range(len(rule.parsed.froms)):
                eng.delete((RULE_FID, rule_id, i))
        return True

    def enable_rule(self, rule_id: str, enabled: bool) -> None:
        self.rules[rule_id].enabled = enabled
        self.rules_rev += 1

    # ----------------------------------------------------- execution

    def apply(self, msg: Message, rule_ids: List[str]) -> int:
        """Run the listed rules against one message; returns how many
        passed their WHERE (emqx_rule_runtime:apply_rules/3)."""
        if not rule_ids:
            return 0
        env = build_env(msg)
        hits = 0
        for rid in rule_ids:
            rule = self.rules.get(rid)
            if rule is None or not rule.enabled:
                continue
            rule.matched += 1
            if not eval_where(rule.parsed.where, env):
                rule.failed += 1
                continue
            rule.passed += 1
            hits += 1
            selected = eval_select(rule.parsed, env)
            self._run_actions(rule, selected, msg)
        if self.broker is not None and hits:
            self.broker.metrics.inc("rules.matched", hits)
        return hits

    def apply_batch(
        self, items: List[Tuple[Message, List[str]]], rec=None
    ) -> int:
        """Run rule hits for a whole dispatch window in ONE registry
        pass: the window's messages decode once into shared column
        planes (`WindowColumns`), every lowerable rule's WHERE
        evaluates as a row of the stacked rules x window boolean
        matrix (numpy host twin or the fused device kernel, chosen by
        the match engine's cost EWMAs), and only non-lowerable rules
        (regex/UDF-shaped calls, CASE) degrade — per RULE, not per
        window — to the interpreter over the SAME lazily-materialized
        envs.  Matched/passed/failed counters update once per rule
        and broker metrics flush in one `inc_bulk` pass.

        ``rec`` (the window's profiler record) takes ``rules_extract``
        / ``rules_eval`` sub-stages so the bench can attribute column
        extraction vs matrix evaluation inside the ``rules`` lap."""
        if not items:
            return 0
        msgs = [m for m, _ in items]
        n = len(msgs)
        envs: List[Optional[LazyEnv]] = [None] * n

        def env(i: int) -> LazyEnv:
            e = envs[i]
            if e is None:
                e = envs[i] = LazyEnv(msgs[i])
            return e

        # flatten the sink to (rule-position, msg) pair columns over
        # the rev-stable position space (see __init__): one flatten-
        # cache probe per message on the steady state, with dedup and
        # canonical ordering done by `np.unique` once per DISTINCT
        # raw id list
        use_matrix = (
            self._matrix_enabled and self.eval_force != "scalar"
        )
        stack: Optional[StackedRules] = None
        selstack: Optional[SelectStack] = None
        if use_matrix:
            stack = self._stacked()
            selstack = self._select_stack(stack)
        key = (self.rules_rev, use_matrix)
        if self._flat_key != key:
            self._flat_key = key
            objs = list(self.rules.values())
            n_all = len(objs)
            self._pos_objs = objs
            self._pos_of = {
                r.rule_id: k for k, r in enumerate(objs)
            }
            self._pos_live = np.fromiter(
                (r.enabled for r in objs), bool, n_all
            )
            row_of = stack.row_of if stack is not None else {}
            self._pos_row = np.fromiter(
                (
                    row_of.get(r.rule_id, -1) if r.enabled else -1
                    for r in objs
                ),
                np.int64, n_all,
            )
            self._ids_cache = {}
            sel_progs = selstack.progs if selstack is not None else {}
            self._pos_selp = [
                (
                    (sel_progs[r.rule_id],
                     selstack.planes[r.rule_id])
                    if r.enabled and r.rule_id in sel_progs
                    and r.actions
                    and all(
                        isinstance(a, (SinkAction, AggregateAction))
                        for a in r.actions
                    )
                    else None
                )
                for r in objs
            ]
            # registry churn re-arms the SELECT cost gate
            self._sel_batch_off = False
        objs = self._pos_objs
        n_pos = len(objs)
        pos_of = self._pos_of
        cache = self._ids_cache
        parts: List[np.ndarray] = []
        lens: List[int] = []
        for _, rids in items:
            ck = tuple(rids)
            arr = cache.get(ck)
            if arr is None:
                if len(cache) > 4096:
                    cache.clear()
                arr = cache[ck] = np.unique(np.fromiter(
                    (
                        pos_of[r] for r in rids if r in pos_of
                    ),
                    np.int64,
                ))
            parts.append(arr)
            lens.append(arr.size)
        ppos = (
            np.concatenate(parts) if parts
            else np.zeros(0, np.int64)
        )
        pmsg = np.repeat(np.arange(n, dtype=np.int64), lens)
        plive = self._pos_live[ppos]
        prow = self._pos_row[ppos]
        matrix = None
        cols: Optional[WindowColumns] = None
        if use_matrix:
            known = prow >= 0
            active = np.unique(prow[known])
            # SELECT lane decision: extract the combined WHERE+SELECT
            # path union (WHERE rows' plane indices are a prefix, so
            # the matrix kernels are untouched) and keep raw values
            # whenever some live matched rule has a batched plan
            use_all = (
                selstack.n_lowered > 0
                and self.select_force != "scalar"
                and (
                    self.select_force == "batched"
                    or not self._sel_batch_off
                )
            )
            batch_sel = False
            if use_all and ppos.size:
                selp = self._pos_selp
                batch_sel = any(
                    selp[p] is not None
                    for p in np.unique(ppos[plive]).tolist()
                )
            if active.size or batch_sel:
                t0 = time.perf_counter()
                cols = WindowColumns(
                    msgs,
                    selstack.all_paths if use_all else stack.paths,
                    stack.lit_strings, envs,
                    keep_values=batch_sel,
                )
                t1 = time.perf_counter()
                if cols.has_nan_value:
                    # a literal NaN payload value aliases the num
                    # lane's not-a-number sentinel: this window's
                    # rules take the interpreter (bit-exactness over
                    # speed for a pathological payload)
                    pass
                elif active.size and self.broker is not None:
                    matrix, _path = (
                        self.broker.router.engine.rules_eval_window(
                            stack, self.rules_rev, cols, rows=active
                        )
                    )
                elif active.size:  # standalone: the host twin directly
                    from ..ops.match_kernel import rules_eval_host

                    sub = rules_eval_host(
                        stack.code[active], stack.a0[active],
                        stack.a1[active], stack.a2[active],
                        stack.a3[active], stack.litn[active],
                        cols.lit_ranks, stack.last[active],
                        cols.num, cols.sid, cols.err, cols.prs,
                    )
                    matrix = np.zeros(
                        (stack.n_rules, cols.n), bool
                    )
                    matrix[active] = sub
                if matrix is not None:
                    self._stats["matrix_windows"] += 1
                    if rec is not None:
                        t2 = time.perf_counter()
                        rec.sub("rules_extract", t1 - t0)
                        rec.sub("rules_eval", t2 - t1)
        if matrix is None:
            self._stats["scalar_windows"] += 1
            known = np.zeros(len(ppos), bool)
        passmask = np.zeros(len(ppos), bool)
        if matrix is not None:
            passmask[known] = matrix[prow[known], pmsg[known]]
        # per-RULE interpreter fallback riding the shared lazy envs
        # (one JSON decode per message, window-wide)
        fb = np.nonzero(plive & ~known)[0]
        if fb.size:
            self._stats["fallback_rule_evals"] += int(fb.size)
            ppos_l = ppos.tolist()
            pmsg_l = pmsg.tolist()
            for j in fb.tolist():
                rule = objs[ppos_l[j]]
                passmask[j] = eval_where(
                    rule.parsed.where, env(pmsg_l[j])
                )
        passmask &= plive
        # matched/passed/failed flush: ONE bincount pass over the
        # pair columns, one += per rule TOUCHED this window
        m_cnt = np.bincount(ppos[plive], minlength=n_pos)
        p_cnt = np.bincount(ppos[passmask], minlength=n_pos)
        touched = np.nonzero(m_cnt)[0]
        for pos, mc, pc in zip(
            touched.tolist(),
            m_cnt[touched].tolist(),
            p_cnt[touched].tolist(),
        ):
            rule = objs[pos]
            rule.matched += mc
            rule.passed += pc
            rule.failed += mc - pc
        hits = int(passmask.sum())
        mloc: Counter = Counter()  # one inc_bulk flush per window
        sel = np.nonzero(passmask)[0]
        if sel.size:
            # canonical action order: rule-major in REGISTRY order,
            # message index ascending within a rule — identical
            # across the device / host / scalar-referee paths
            order = np.lexsort((pmsg[sel], ppos[sel]))
            sel_l = sel[order].tolist()
            ppos_l = ppos.tolist()
            pmsg_l = pmsg.tolist()
            selp = self._pos_selp
            use_batched = cols is not None and cols.vals is not None
            t_act0 = time.perf_counter()  # hoisted (no clocks in loop)
            rows_b = 0
            rows_s = 0
            k = 0
            n_sel = len(sel_l)
            while k < n_sel:
                # consecutive run of pairs for ONE rule (sel_l is
                # rule-major after the lexsort)
                pos = ppos_l[sel_l[k]]
                k2 = k + 1
                while k2 < n_sel and ppos_l[sel_l[k2]] == pos:
                    k2 += 1
                rule = objs[pos]
                if not rule.actions:
                    # nothing consumes the SELECT columns: skip the
                    # per-hit projection entirely (counter-only rules)
                    k = k2
                    continue
                plan = selp[pos] if use_batched else None
                if plan is not None:
                    rows = [pmsg_l[sel_l[t]] for t in range(k, k2)]
                    self._run_rule_batched(rule, plan, cols, rows, mloc)
                    rows_b += k2 - k
                else:
                    for t in range(k, k2):
                        i = pmsg_l[sel_l[t]]
                        selected = eval_select(rule.parsed, env(i))
                        self._run_actions(rule, selected, msgs[i], mloc)
                    rows_s += k2 - k
                k = k2
            t_act1 = time.perf_counter()
            self._sel_lane_account(rows_b, rows_s, t_act1 - t_act0)
        if hits:
            mloc["rules.matched"] += hits
        if self.broker is not None and mloc:
            self.broker.metrics.inc_bulk(mloc)
        return hits

    def _sel_lane_account(
        self, rows_b: int, rows_s: int, dt: float
    ) -> None:
        """Fold one window's SELECT+action lap into the per-lane cost
        EWMAs (sampled on single-lane windows only, so the figures
        aren't cross-contaminated) and trip the batched lane's cost
        breaker when it measures materially slower than the scalar
        referee — re-armed by registry churn, overridden by
        ``select_force``."""
        if rows_b and not rows_s:
            us = dt * 1e6 / rows_b
            self._sel_us_b = (
                us if self._sel_us_b is None
                else 0.2 * us + 0.8 * self._sel_us_b
            )
            self._sel_n_b += 1
        elif rows_s and not rows_b:
            us = dt * 1e6 / rows_s
            self._sel_us_s = (
                us if self._sel_us_s is None
                else 0.2 * us + 0.8 * self._sel_us_s
            )
            self._sel_n_s += 1
        if rows_b:
            self._stats["select_batched_rows"] += rows_b
        if rows_s:
            self._stats["select_scalar_rows"] += rows_s
        if (
            self.select_force is None
            and not self._sel_batch_off
            and self._sel_n_b >= 16
            and self._sel_n_s >= 16
            and self._sel_us_b is not None
            and self._sel_us_s is not None
            and self._sel_us_b > self._sel_us_s * 1.5
        ):
            self._sel_batch_off = True
            self._stats["select_ewma_off"] += 1

    def _run_rule_batched(
        self,
        rule: Rule,
        plan: tuple,
        cols: WindowColumns,
        rows: List[int],
        mloc: Counter,
    ) -> None:
        """One rule's whole matched-row set through its lowered
        SELECT and window-shaped actions: one `materialize_rows` pass
        over the shared column planes, then ONE bulk handoff per
        (action, window) — `BufferWorker.enqueue_batch` for sinks,
        one `Aggregator.push` for aggregate actions.  Counter totals
        and per-sink query streams match the scalar referee exactly
        (same values, same order); only the cross-ACTION interleave
        differs (batched emits action-major within a rule)."""
        prog, planes = plan
        names, colvals = materialize_rows(prog, planes, cols, rows)
        n = len(rows)
        resources = (
            self.broker.resources if self.broker is not None else None
        )
        for action in rule.actions:
            try:
                if isinstance(action, AggregateAction):
                    action.aggregator.push([
                        dict(zip(names, row)) for row in zip(*colvals)
                    ])
                else:  # SinkAction (plan eligibility guarantees it)
                    if resources is None:
                        raise RuntimeError(
                            "sink action without a broker"
                        )
                    worker = resources.get(action.resource_id)
                    if worker is None:
                        raise RuntimeError(
                            f"unknown resource {action.resource_id!r}"
                        )
                    if action.payload is not None:
                        prog_t = getattr(action, "_payload_prog", None)
                        if prog_t is None:
                            prog_t = compile_template(action.payload)
                        colmap: Dict[str, Any] = {}
                        for nm, col in zip(names, colvals):
                            colmap[nm] = col
                        queries = prog_t.render_rows(colmap, n)
                    else:
                        queries = [
                            _json.dumps(
                                dict(zip(names, row)), default=str
                            )
                            for row in zip(*colvals)
                        ]
                    worker.enqueue_batch(queries)
                rule.actions_success += n
                mloc["actions.success"] += n
                mloc["actions.batched"] += n
            except Exception as exc:
                rule.actions_failed += n
                mloc["actions.failed"] += n
                log.warning(
                    "rule %s batched action %s failed: %s",
                    rule.rule_id,
                    getattr(action, "kind", action),
                    exc,
                )

    def _run_actions(
        self,
        rule: Rule,
        selected: Dict[str, Any],
        msg: Message,
        mloc: Optional[Counter] = None,
    ) -> None:
        for action in rule.actions:
            try:
                self._run_action(action, selected, msg)
                rule.actions_success += 1
                if mloc is not None:
                    mloc["actions.success"] += 1
                elif self.broker is not None:
                    self.broker.metrics.inc("actions.success")
            except Exception as exc:
                rule.actions_failed += 1
                if mloc is not None:
                    mloc["actions.failed"] += 1
                elif self.broker is not None:
                    self.broker.metrics.inc("actions.failed")
                log.warning(
                    "rule %s action %s failed: %s",
                    rule.rule_id,
                    getattr(action, "kind", action),
                    exc,
                )

    def _run_action(
        self, action: Action, selected: Dict[str, Any], msg: Message
    ) -> None:
        if isinstance(action, RepublishAction):
            depth = int(msg.headers.get("republish_depth", 0))
            if depth >= MAX_REPUBLISH_DEPTH:
                raise RuntimeError("republish depth cap hit (rule loop?)")
            tprog = getattr(action, "_topic_prog", None)
            if tprog is None:
                tprog = compile_template(action.topic)
            pprog = getattr(action, "_payload_prog", None)
            if pprog is None:
                pprog = compile_template(action.payload)
            out = Message(
                topic=tprog.render(selected),
                payload=pprog.render(selected).encode(),
                qos=action.qos,
                retain=action.retain,
                from_client=msg.from_client,
                from_username=msg.from_username,
                headers={"republish_depth": depth + 1},
            )
            if self.broker is None:
                raise RuntimeError("republish without a broker")
            self.broker.publish(out)
        elif isinstance(action, ConsoleAction):
            log.info("rule output: %s", selected)
        elif isinstance(action, FunctionAction):
            action.fn(selected, msg)
        elif isinstance(action, AggregateAction):
            action.aggregator.push([selected])
        elif isinstance(action, SinkAction):
            if self.broker is None:
                raise RuntimeError("sink action without a broker")
            worker = self.broker.resources.get(action.resource_id)
            if worker is None:
                raise RuntimeError(
                    f"unknown resource {action.resource_id!r}"
                )
            if action.payload is not None:
                pprog = getattr(action, "_payload_prog", None)
                if pprog is None:
                    pprog = compile_template(action.payload)
                query: Any = pprog.render(selected)
            else:
                query = _json.dumps(selected, default=str)
            worker.enqueue(query)
        else:
            raise RuntimeError(f"unknown action {action!r}")

    def info(self) -> List[Dict[str, Any]]:
        return [
            {
                "id": r.rule_id,
                "sql": r.sql,
                "enabled": r.enabled,
                "description": r.description,
                **r.metrics(),
            }
            for r in self.rules.values()
        ]

    def stats(self) -> Dict[str, Any]:
        """The rule-eval gauge surface (`MatchEngine.stats()`-form):
        lowered-vs-fallback registry split, path window counts, the
        engine's per-cell cost EWMAs and breaker state — exposed
        through ``/metrics``, ``GET /api/v5/rules`` and $SYS."""
        stack = self._stacked()
        selstack = self._select_stack(stack)
        out: Dict[str, Any] = {
            "rules": len(self.rules),
            "lowered": stack.n_lowered,
            "program_rows": stack.n_rules,  # after program dedup
            "fallback": len(stack.fallback),
            "matrix_enabled": self._matrix_enabled,
            "matrix_windows": self._stats["matrix_windows"],
            "scalar_windows": self._stats["scalar_windows"],
            "fallback_rule_evals": self._stats["fallback_rule_evals"],
            # output half (PR 20): lowered SELECT registry split, the
            # per-lane row counts and cost EWMAs, breaker state
            "select_lowered": selstack.n_lowered,
            "select_batched_rows": self._stats["select_batched_rows"],
            "select_scalar_rows": self._stats["select_scalar_rows"],
            "select_ewma_off": self._stats["select_ewma_off"],
            "select_batched_us_ewma": self._sel_us_b,
            "select_scalar_us_ewma": self._sel_us_s,
            "select_batch_disabled": self._sel_batch_off,
        }
        if self.broker is not None:
            eng = self.broker.router.engine
            out["host_windows"] = eng._rul_stats["host_windows"]
            out["dev_windows"] = eng._rul_stats["dev_windows"]
            out["dev_errors"] = eng._rul_stats["dev_errors"]
            out["host_us_ewma"] = eng._rul_host_us
            out["dev_us_ewma"] = eng._rul_dev_us
            out["breaker_open"] = eng.breaker_open
        return out
