"""Batched SELECT lowering + precompiled ``${a.b}`` templates.

The output half of the rule matrix (the WHERE half lives in
`predicate.py`/`columns.py`): a lowerable SELECT list — field
projections, literals, arithmetic, ``*`` — compiles ONCE per registry
revision into a `SelectProgram` whose inputs are raw-value planes on
the shared `WindowColumns`, so one pass over a window materializes
action payloads for every matched row of every lowered rule.  Rules
whose SELECT uses nodes the compiler doesn't cover (function calls,
CASE, comparisons) degrade per RULE to the scalar interpreter
(`runtime.eval_select`), which stays the property-tested referee.

Placeholder templates (``${a.b}``, `emqx_placeholder` semantics) get
the same treatment: `compile_template` parses a template ONCE into a
segment program (literal chunks + resolved path tuples) instead of
re-walking the regex and re-splitting every dotted path per message.
`TemplateProgram.render` is the scalar form (bit-identical to the old
`render_template`, fuzz-pinned by tests/test_rules_select.py) and
`render_rows` the column form used by the batched egress.

Value semantics are anchored to the interpreter on purpose:

- projection/star values come from a raw-value plane filled during
  the one `WindowColumns` walk (``keep_values``); a lookup error or a
  missing key is ``None``, exactly `eval_select`'s catch;
- arithmetic closures call `runtime.arith_op` — the SAME function the
  interpreter calls — so int-ness preservation (``json.dumps(5)`` !=
  ``json.dumps(5.0)``), string ``+`` concat and div-by-zero ->
  ``None`` hold bit-identically;
- expression operands distinguish lookup ERROR (raises, field ->
  ``None``) from missing (operand is ``None`` -> arithmetic raises),
  via the err lane, like `lookup_var`.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .runtime import (
    EvalError, _PayloadStr, _STAR_FIELDS, _default_name, arith_op,
)
from .sql import ParsedSql

_PLACEHOLDER = re.compile(r"\$\{([^}]+)\}")

_MISSING = object()


def stringify(v: Any) -> str:
    """Template placeholder value -> text (emqx_placeholder parity;
    the exact `render_template` substitution semantics, shared by the
    scalar and column renderers)."""
    t = type(v)
    if t is str:  # exact-type fast path: the dominant case by far
        return v
    if t is int:
        return str(v)
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    return str(v)


class TemplateProgram:
    """One parsed ``${a.b}`` template: an alternating sequence of
    literal string chunks and pre-split path tuples."""

    __slots__ = ("template", "parts", "n_slots", "_fmt")

    def __init__(self, template: str) -> None:
        self.template = template
        parts: List[Any] = []
        pos = 0
        n_slots = 0
        for m in _PLACEHOLDER.finditer(template):
            if m.start() > pos:
                parts.append(template[pos:m.start()])
            parts.append(tuple(m.group(1).split(".")))
            n_slots += 1
            pos = m.end()
        if pos < len(template):
            parts.append(template[pos:])
        self.parts = tuple(parts)
        self.n_slots = n_slots
        # %-format twin of ``parts`` (literals escaped): the column
        # renderer substitutes whole ROWS at C speed with one
        # ``fmt % tuple`` per row instead of a per-part join
        self._fmt = "".join(
            p.replace("%", "%%") if p.__class__ is str else "%s"
            for p in parts
        )

    def render(self, data: Dict[str, Any]) -> str:
        """Scalar substitution against one SELECTed row."""
        if not self.n_slots:
            return self.template
        out: List[str] = []
        for part in self.parts:
            if part.__class__ is str:
                out.append(part)
                continue
            cur: Any = data
            for seg in part:
                if isinstance(cur, dict) and seg in cur:
                    cur = cur[seg]
                else:
                    cur = _MISSING
                    break
            out.append(
                "undefined" if cur is _MISSING else stringify(cur)
            )
        return "".join(out)

    def render_rows(
        self, cols: Dict[str, Sequence[Any]], n: int
    ) -> List[str]:
        """Column substitution: one rendered string per row, reading
        each placeholder's head from the SELECTed output columns.
        Bit-identical to calling `render` on each row's dict."""
        if not self.n_slots:
            return [self.template] * n
        vcols: List[List[str]] = []
        for part in self.parts:
            if part.__class__ is str:
                continue
            col = cols.get(part[0], _MISSING)
            if col is _MISSING:
                vcols.append(["undefined"] * n)
            elif len(part) == 1:
                vcols.append([stringify(v) for v in col])
            else:
                rest = part[1:]
                vals: List[str] = []
                for v in col:
                    cur: Any = v
                    for seg in rest:
                        if isinstance(cur, dict) and seg in cur:
                            cur = cur[seg]
                        else:
                            cur = _MISSING
                            break
                    vals.append(
                        "undefined" if cur is _MISSING
                        else stringify(cur)
                    )
                vcols.append(vals)
        fmt = self._fmt
        if len(vcols) == 1:
            return [fmt % (v,) for v in vcols[0]]
        return [fmt % t for t in zip(*vcols)]


# compiled-template cache: action templates are a small fixed set per
# registry, but ad-hoc render_template callers ride the same cache
_TEMPLATE_CACHE: Dict[str, TemplateProgram] = {}
_TEMPLATE_CACHE_CAP = 4096


def compile_template(template: str) -> TemplateProgram:
    prog = _TEMPLATE_CACHE.get(template)
    if prog is None:
        if len(_TEMPLATE_CACHE) >= _TEMPLATE_CACHE_CAP:
            _TEMPLATE_CACHE.clear()
        prog = _TEMPLATE_CACHE[template] = TemplateProgram(template)
    return prog


# ------------------------------------------------------ SELECT lowering


class _Unsupported(Exception):
    pass


_ARITH_SYMS = ("+", "-", "*", "/", "div", "mod")


def _compile_expr(
    expr: tuple, reg: Callable[[Tuple[str, ...]], int]
) -> Callable[[tuple, tuple], Any]:
    """AST subtree -> closure over one row's gathered operand values
    (``vals``) and error flags (``errs``), indexed by the local path
    slots ``reg`` hands out.  Raises `_Unsupported` on nodes outside
    the lowerable subset (calls, CASE, comparisons, IN, NOT)."""
    kind = expr[0]
    if kind == "lit":
        v = expr[1]
        return lambda vals, errs: v
    if kind == "var":
        k = reg(expr[1])

        def var_fn(vals, errs, _k=k):
            if errs[_k]:
                # `lookup_var` raised for this row: the interpreter's
                # eval_expr propagates, so the compiled form does too
                raise EvalError("lookup error")
            return vals[_k]

        return var_fn
    if kind == "neg":
        f = _compile_expr(expr[1], reg)

        def neg_fn(vals, errs, _f=f):
            v = _f(vals, errs)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise EvalError(f"negating non-number {v!r}")
            return -v

        return neg_fn
    if kind == "op" and expr[1] in _ARITH_SYMS:
        sym = expr[1]
        fa = _compile_expr(expr[2], reg)
        fb = _compile_expr(expr[3], reg)
        return lambda vals, errs: arith_op(
            sym, fa(vals, errs), fb(vals, errs)
        )
    raise _Unsupported(kind)


class SelectProgram:
    """One rule's lowered SELECT list.

    ``fields`` entries are ``(kind, name, arg)``:

    - ``("var", name, slot)`` — projection of local path slot
    - ``("lit", name, value)`` — constant column
    - ``("expr", name, fn)`` — compiled arithmetic closure
    - ``("star", None, ((name, slot), ...))`` — ``*`` expansion over
      the eight `_STAR_FIELDS`

    ``paths`` is the tuple of var paths the program reads; slots index
    into it.  ``has_expr`` gates the error-lane gather: only compiled
    expressions distinguish lookup-error from missing (projections
    emit ``None`` for both)."""

    __slots__ = ("fields", "paths", "has_expr")

    def __init__(self, fields: tuple, paths: tuple) -> None:
        self.fields = fields
        self.paths = paths
        self.has_expr = any(f[0] == "expr" for f in fields)


def compile_select(parsed: ParsedSql) -> Optional[SelectProgram]:
    """Lower a SELECT list, or None when any field uses nodes outside
    the compiled subset (the rule then degrades to the interpreter)."""
    paths: List[Tuple[str, ...]] = []
    pix: Dict[Tuple[str, ...], int] = {}

    def reg(path: Tuple[str, ...]) -> int:
        k = pix.get(path)
        if k is None:
            k = pix[path] = len(paths)
            paths.append(path)
        return k

    fields: List[tuple] = []
    try:
        for f in parsed.fields:
            if f.star:
                fields.append((
                    "star", None,
                    tuple((k, reg((k,))) for k in _STAR_FIELDS),
                ))
                continue
            name = f.alias or _default_name(f.expr)
            e = f.expr
            if e[0] == "lit":
                fields.append(("lit", name, e[1]))
            elif e[0] == "var":
                fields.append(("var", name, reg(e[1])))
            else:
                fields.append(("expr", name, _compile_expr(e, reg)))
    except _Unsupported:
        return None
    return SelectProgram(tuple(fields), tuple(paths))


class SelectStack:
    """The enabled registry's lowered SELECT programs over one shared
    path union: ``all_paths`` extends the WHERE stack's path list (the
    WHERE rows' plane indices stay valid — SELECT paths are strictly
    APPENDED), ``planes[rule_id]`` maps each program's local slots to
    plane rows in that combined space."""

    __slots__ = ("progs", "planes", "all_paths", "n_lowered")

    def __init__(self, progs, planes, all_paths) -> None:
        self.progs: Dict[str, SelectProgram] = progs
        self.planes: Dict[str, Tuple[int, ...]] = planes
        self.all_paths: Tuple[Tuple[str, ...], ...] = all_paths
        self.n_lowered = len(progs)


def build_select_stack(
    rules: Sequence[Tuple[str, ParsedSql]],
    base_paths: Sequence[Tuple[str, ...]],
) -> SelectStack:
    paths: List[Tuple[str, ...]] = list(base_paths)
    ix: Dict[Tuple[str, ...], int] = {
        p: k for k, p in enumerate(paths)
    }
    progs: Dict[str, SelectProgram] = {}
    planes: Dict[str, Tuple[int, ...]] = {}
    for rid, parsed in rules:
        prog = compile_select(parsed)
        if prog is None:
            continue
        pl: List[int] = []
        for p in prog.paths:
            k = ix.get(p)
            if k is None:
                k = ix[p] = len(paths)
                paths.append(p)
            pl.append(k)
        progs[rid] = prog
        planes[rid] = tuple(pl)
    return SelectStack(progs, planes, tuple(paths))


def materialize_rows(
    prog: SelectProgram,
    planes: Tuple[int, ...],
    cols,  # WindowColumns built with keep_values=True
    rows: Sequence[int],
) -> Tuple[List[str], List[List[Any]]]:
    """One rule's SELECT over its matched window rows in one pass:
    gather the program's value/err planes for ``rows``, then produce
    one output column per SELECT field.  Returns ``(names, columns)``
    aligned with the (star-expanded) field list; a per-row dict built
    as ``dict(zip(names, row))`` is bit-identical to
    `runtime.eval_select` (duplicate names keep first position, last
    value — plain dict-assignment semantics)."""
    vals_planes = cols.vals
    gv: List[List[Any]] = []
    ge: List[List[bool]] = []
    for g in planes:
        plane = vals_planes[g]
        gv.append([plane[i] for i in rows])
    if prog.has_expr:
        # scalar-index the numpy err rows: matched sets are usually a
        # few rows, where fancy-index + tolist costs more than it saves
        err_planes = cols.err
        for g in planes:
            erow = err_planes[g]
            ge.append([erow[i] for i in rows])
    n = len(rows)
    names: List[str] = []
    colvals: List[List[Any]] = []
    vrows = erows = None
    for kind, name, arg in prog.fields:
        if kind == "star":
            for sname, k in arg:
                names.append(sname)
                colvals.append(gv[k])
        elif kind == "var":
            names.append(name)
            colvals.append(gv[arg])
        elif kind == "lit":
            names.append(name)
            colvals.append([arg] * n)
        else:  # compiled expression
            if vrows is None:  # one transpose, shared by every expr
                vrows = list(zip(*gv)) if gv else [()] * n
                erows = list(zip(*ge)) if ge else [()] * n
            fn = arg
            out: List[Any] = []
            for r in range(n):
                try:
                    v = fn(vrows[r], erows[r])
                except (EvalError, TypeError, ValueError):
                    v = None
                if isinstance(v, _PayloadStr):
                    v = str(v)
                out.append(v)
            names.append(name)
            colvals.append(out)
    return names, colvals
