"""Batched WHERE-predicate compiler: rule conditions over message
columns as one vectorized step.

The reference interprets each rule's WHERE per message
(emqx_rule_runtime.erl:60-100).  Here a WHERE AST compiles — when its
node set allows — into a column program evaluated over the whole
publish micro-batch at once (jax.jit; numpy fallback off-device), the
SURVEY §7 "WHERE predicate eval is the second kernel target" plan.

Semantics must match the interpreter (`runtime.eval_where`) exactly:

  * ordering comparisons / arithmetic on a null or non-numeric value
    ERROR, and an error makes the whole WHERE false — but
    short-circuiting means an error on the right of an
    already-decided and/or never surfaces.  Captured by compiling
    every boolean node to a (T, F) pair — "provably true" /
    "provably false without error" under short-circuit order:

        ordering cmp:  T = defined & cmp,  F = defined & ~cmp
        not:           (T, F) -> (F, T)
        and:           T = Tl & Tr,        F = Fl | (Tl & Fr)
        or:            T = Tl | (Fl & Tr), F = Fl & Fr

  * equality (`=`, `!=`) over plain operands (var / literal) is TOTAL:
    null or cross-type operands are simply unequal (no error) — so
    `missing != 'y'` is TRUE.  Equality over a compound side (an
    arithmetic expression) inherits that side's error semantics.
  * booleans are their own type: `retain = 1` is false even when
    retain is true (Erlang term inequality in the reference).

Columns are dual-typed: each var extracts to a float lane (NaN = not a
number/undefined) and a dictionary-encoded id lane (-1 = not a
string/bool; bools get reserved ids).  Comparisons pick lanes by
operand type.  Unsupported nodes (function calls, CASE, bare vars in
boolean position) make ``compile_where`` return None and the caller
falls back to the interpreter.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .runtime import lookup_var

# reserved string-lane ids for booleans ('\x00' cannot occur in MQTT
# UTF-8 strings, so these keys cannot collide with real payloads)
_TRUE_KEY = "\x00true"
_FALSE_KEY = "\x00false"


class _Unsupported(Exception):
    pass


class PredicateProgram:
    """A compiled WHERE: collect var columns, evaluate batched."""

    def __init__(self, where: tuple, var_paths: List[Tuple[str, ...]]):
        self.where = where
        self.var_paths = var_paths
        self._jit = None

    # ---------------------------------------------------- extraction

    def extract_columns(
        self, envs: Sequence[Dict[str, Any]]
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, int]]:
        """Host side: pull each var path from each env into dual-typed
        columns; strings (and bools) dictionary-encoded per batch."""
        n = len(envs)
        sdict: Dict[str, int] = {_TRUE_KEY: 0, _FALSE_KEY: 1}
        num = {p: np.full(n, np.nan, np.float64) for p in self.var_paths}
        sid = {p: np.full(n, -1, np.int32) for p in self.var_paths}
        # a lookup ERROR (e.g. descending into a non-JSON payload) is
        # not the same as undefined: the interpreter errors when it
        # evaluates that var, making the WHERE false — tracked as a
        # third lane so total equality stays oracle-equal
        err = {p: np.zeros(n, bool) for p in self.var_paths}
        for i, env in enumerate(envs):
            for p in self.var_paths:
                try:
                    v = lookup_var(env, p)
                except Exception:
                    err[p][i] = True
                    continue
                if isinstance(v, bool):
                    sid[p][i] = sdict[_TRUE_KEY if v else _FALSE_KEY]
                elif isinstance(v, (int, float)):
                    num[p][i] = v
                elif isinstance(v, str):
                    key = str(v)
                    if key not in sdict:
                        sdict[key] = len(sdict)
                    sid[p][i] = sdict[key]
        cols = {}
        for p in self.var_paths:
            cols["n:" + "/".join(p)] = num[p]
            cols["s:" + "/".join(p)] = sid[p]
            cols["e:" + "/".join(p)] = err[p]
        return cols, sdict

    # ---------------------------------------------------- evaluation

    def eval_batch(
        self, envs: Sequence[Dict[str, Any]], use_jax: bool = False
    ) -> np.ndarray:
        cols, sdict = self.extract_columns(envs)
        lit_ids = _literal_ids(self.where, sdict)
        if use_jax and self._f32_safe(cols):
            import jax

            if self._jit is None:
                import jax.numpy as jnp

                def fn(cols, lit_ids):
                    t, _ = _eval(self.where, cols, lit_ids, jnp)
                    return t

                self._jit = jax.jit(fn)
            return np.asarray(self._jit(cols, lit_ids))
        t, _ = _eval(self.where, cols, lit_ids, np)
        return np.asarray(t)

    def _f32_safe(self, cols: Dict[str, np.ndarray]) -> bool:
        """The device path computes in float32 (jax default / TPU
        native); use it only when every numeric value round-trips
        exactly AND the WHERE performs no arithmetic — an arithmetic
        RESULT can lose precision even when every input round-trips
        (16777216+1 == 16777216 in f32), so any arith stays on the
        float64 host path.  Millisecond timestamps are the canonical
        input offender."""
        if _has_arith(self.where):
            return False
        lits: List[float] = []
        _num_literals(self.where, lits)
        for v in lits:
            if float(np.float32(v)) != v:
                return False
        for name, a in cols.items():
            if name.startswith("n:"):
                finite = a[np.isfinite(a)]
                if not (finite == finite.astype(np.float32)).all():
                    return False
        return True


def _has_arith(expr: tuple) -> bool:
    kind = expr[0]
    if kind == "neg":
        return True
    if kind == "op":
        if expr[1] in ("+", "-", "*", "/", "div", "mod"):
            return True
        return _has_arith(expr[2]) or _has_arith(expr[3])
    if kind == "not":
        return _has_arith(expr[1])
    if kind == "in":
        return _has_arith(expr[1]) or any(_has_arith(e) for e in expr[2])
    return False


def _num_literals(expr: tuple, out: List[float]) -> None:
    kind = expr[0]
    if kind == "lit" and isinstance(expr[1], (int, float)) and not isinstance(
        expr[1], bool
    ):
        out.append(float(expr[1]))
    elif kind == "op":
        _num_literals(expr[2], out)
        _num_literals(expr[3], out)
    elif kind in ("not", "neg"):
        _num_literals(expr[1], out)
    elif kind == "in":
        _num_literals(expr[1], out)
        for e in expr[2]:
            _num_literals(e, out)


def _string_literals(expr: tuple, out: Set[str]) -> None:
    kind = expr[0]
    if kind == "lit" and isinstance(expr[1], str):
        out.add(expr[1])
    elif kind == "op":
        _string_literals(expr[2], out)
        _string_literals(expr[3], out)
    elif kind in ("not", "neg"):
        _string_literals(expr[1], out)
    elif kind == "in":
        _string_literals(expr[1], out)
        for e in expr[2]:
            _string_literals(e, out)


def _literal_ids(where: tuple, sdict: Dict[str, int]) -> Dict[str, int]:
    """Map string literals to batch-dict ids (-2 = absent from batch:
    matches nothing, distinct from -1 'not a string')."""
    lits: Set[str] = set()
    _string_literals(where, lits)
    return {s: sdict.get(s, -2) for s in lits}


def _collect_vars(expr: tuple, out: List[Tuple[str, ...]]) -> None:
    kind = expr[0]
    if kind == "var":
        if expr[1] not in out:
            out.append(expr[1])
    elif kind == "op":
        _collect_vars(expr[2], out)
        _collect_vars(expr[3], out)
    elif kind in ("not", "neg"):
        _collect_vars(expr[1], out)
    elif kind == "in":
        _collect_vars(expr[1], out)
        for e in expr[2]:
            _collect_vars(e, out)
    elif kind in ("call", "case"):
        raise _Unsupported(kind)


def compile_where(where: Optional[tuple]) -> Optional[PredicateProgram]:
    """Compile if every node is in the supported subset, else None."""
    if where is None:
        return None
    try:
        paths: List[Tuple[str, ...]] = []
        _collect_vars(where, paths)
        _check_bool(where)
    except _Unsupported:
        return None
    return PredicateProgram(where, paths)


def _check_bool(expr: tuple) -> None:
    """Validate a boolean-position node."""
    kind = expr[0]
    if kind == "lit" and isinstance(expr[1], bool):
        return
    if kind == "not":
        return _check_bool(expr[1])
    if kind == "in":
        lt = _check_val(expr[1])
        for e in expr[2]:
            et = _check_val(e)
            if "bool" in (lt, et):
                raise _Unsupported("bool in IN")
            if lt != "var" and et != "var" and et != lt:
                raise _Unsupported("mixed IN list")
        return
    if kind == "op":
        sym = expr[1]
        if sym in ("and", "or"):
            _check_bool(expr[2])
            _check_bool(expr[3])
            return
        if sym in ("=", "!=", ">", "<", ">=", "<="):
            lt, rt = _check_val(expr[2]), _check_val(expr[3])
            if "bool" in (lt, rt):
                raise _Unsupported("bool compare")
            if lt == "str" and rt == "str":
                raise _Unsupported("str-str compare is constant")
            if "str" in (lt, rt):
                other = rt if lt == "str" else lt
                if other != "var":
                    raise _Unsupported("str vs num compare")
                if sym not in ("=", "!="):
                    raise _Unsupported("string ordering")
            return
    raise _Unsupported(f"{kind} at boolean position")


def _check_val(expr: tuple) -> str:
    """Validate a value-position node -> 'num' | 'str' | 'bool' |
    'var' (dual-typed) | 'expr' (compound numeric)."""
    kind = expr[0]
    if kind == "lit":
        v = expr[1]
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, (int, float)):
            return "num"
        if isinstance(v, str):
            return "str"
        raise _Unsupported(f"literal {v!r}")
    if kind == "var":
        return "var"
    if kind == "neg":
        t = _check_val(expr[1])
        if t not in ("num", "var", "expr"):
            raise _Unsupported("neg of non-number")
        return "expr"
    if kind == "op" and expr[1] in ("+", "-", "*", "/", "div", "mod"):
        for sub in (expr[2], expr[3]):
            if _check_val(sub) not in ("num", "var", "expr"):
                raise _Unsupported("arith on non-numbers")
        return "expr"
    raise _Unsupported(kind)


def _eval(expr: tuple, cols, lit_ids, xp):
    """Boolean-position evaluation -> (T, F) masks."""
    kind = expr[0]
    if kind == "op" and expr[1] in ("and", "or"):
        tl, fl = _eval(expr[2], cols, lit_ids, xp)
        tr, fr = _eval(expr[3], cols, lit_ids, xp)
        if expr[1] == "and":
            return tl & tr, fl | (tl & fr)
        return tl | (fl & tr), fl & fr
    if kind == "not":
        t, f = _eval(expr[1], cols, lit_ids, xp)
        return f, t
    if kind == "lit":  # bool literal (validated)
        n = _batch_len(cols)
        full = xp.full(n, bool(expr[1]))
        return full, ~full
    if kind == "in":
        ts = fs = None
        for e in expr[2]:
            t, f = _eval(("op", "=", expr[1], e), cols, lit_ids, xp)
            ts = t if ts is None else (ts | (fs & t))
            fs = f if fs is None else (fs & f)
        return ts, fs
    if kind == "op":
        return _eval_cmp(expr, cols, lit_ids, xp)
    raise AssertionError(f"non-boolean node at boolean position: {kind}")


def _is_simple(expr: tuple) -> bool:
    return expr[0] in ("lit", "var")


def _eval_cmp(expr: tuple, cols, lit_ids, xp):
    sym, le, re_ = expr[1], expr[2], expr[3]
    lstr = le[0] == "lit" and isinstance(le[1], str)
    rstr = re_[0] == "lit" and isinstance(re_[1], str)
    if lstr or rstr:
        # string-literal equality against a var's id lane; TOTAL except
        # when the var lookup itself ERRORED (interpreter: WHERE false)
        lit, var = (le, re_) if lstr else (re_, le)
        ids = cols["s:" + "/".join(var[1])]
        erv = cols["e:" + "/".join(var[1])]
        lid = lit_ids[lit[1]]
        eq = ~erv & (ids == lid)
        ne = ~erv & (ids != lid)
        return (eq, ne) if sym == "=" else (ne, eq)

    if sym in ("=", "!="):
        lv, ld = _num_eval_pair(le, cols, lit_ids, xp)
        rv, rd = _num_eval_pair(re_, cols, lit_ids, xp)
        eq = ld & rd & (lv == rv)
        if le[0] == "var" and re_[0] == "var":
            # var-var equality also matches on the id lanes
            li = cols["s:" + "/".join(le[1])]
            ri = cols["s:" + "/".join(re_[1])]
            eq = eq | ((li >= 0) & (li == ri))
        # equality itself is total; but a lookup ERROR on a simple var
        # (vs merely undefined) poisons the row, and a COMPOUND side
        # contributes its own error semantics (sub-expression may fail)
        ok = None
        for side in (le, re_):
            if side[0] == "var":
                e = cols["e:" + "/".join(side[1])]
                ok = ~e if ok is None else (ok & ~e)
        if ok is None:
            ok = xp.full(_batch_len(cols), True)
        cd = None
        for side, d in ((le, ld), (re_, rd)):
            if not _is_simple(side):
                cd = d if cd is None else (cd & d)
        if cd is None:
            return (
                (eq & ok, ~eq & ok) if sym == "=" else (~eq & ok, eq & ok)
            )
        return (
            (eq & ok, cd & ~eq & ok)
            if sym == "="
            else (cd & ~eq & ok, eq & ok)
        )

    # ordering: error semantics
    lv, ld = _num_eval_pair(le, cols, lit_ids, xp)
    rv, rd = _num_eval_pair(re_, cols, lit_ids, xp)
    d = ld & rd
    cmp = {
        ">": lv > rv,
        "<": lv < rv,
        ">=": lv >= rv,
        "<=": lv <= rv,
    }[sym]
    return d & cmp, d & ~cmp


def _num_eval_pair(expr: tuple, cols, lit_ids, xp):
    """Numeric (value, defined) evaluation."""
    kind = expr[0]
    if kind == "lit":
        n = _batch_len(cols)
        dt = np.float64 if xp is np else np.float32
        v = xp.full(n, float(expr[1]), dt)
        return v, xp.full(n, True)
    if kind == "var":
        v = cols["n:" + "/".join(expr[1])]
        return v, ~xp.isnan(v)
    if kind == "neg":
        v, d = _num_eval_pair(expr[1], cols, lit_ids, xp)
        return -v, d
    if kind == "op":
        sym = expr[1]
        lv, ld = _num_eval_pair(expr[2], cols, lit_ids, xp)
        rv, rd = _num_eval_pair(expr[3], cols, lit_ids, xp)
        d = ld & rd
        if sym == "+":
            return lv + rv, d
        if sym == "-":
            return lv - rv, d
        if sym == "*":
            return lv * rv, d
        if sym == "/":
            ok = rv != 0
            return xp.where(ok, lv / xp.where(ok, rv, 1), 0), d & ok
        # div/mod: the interpreter truncates BOTH operands to int
        # first (int(a) // int(b), int(a) % int(b)), then floor-divides
        ta = xp.trunc(lv)
        tb = xp.trunc(rv)
        ok = tb != 0
        safe = xp.where(ok, tb, 1)
        q = xp.floor(ta / safe)
        if sym == "div":
            return q, d & ok
        return ta - q * safe, d & ok
    raise AssertionError(f"bad numeric node {kind}")


def _batch_len(cols) -> int:
    return next(iter(cols.values())).shape[0]
