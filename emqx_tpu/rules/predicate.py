"""Batched WHERE-predicate compiler: rule conditions over message
columns as one vectorized step.

The reference interprets each rule's WHERE per message
(emqx_rule_runtime.erl:60-100).  Here a WHERE AST compiles — when its
node set allows — into a column program evaluated over the whole
publish micro-batch at once (jax.jit; numpy fallback off-device), the
SURVEY §7 "WHERE predicate eval is the second kernel target" plan.

Semantics must match the interpreter (`runtime.eval_where`) exactly:

  * ordering comparisons / arithmetic on a null or non-numeric value
    ERROR, and an error makes the whole WHERE false — but
    short-circuiting means an error on the right of an
    already-decided and/or never surfaces.  Captured by compiling
    every boolean node to a (T, F) pair — "provably true" /
    "provably false without error" under short-circuit order:

        ordering cmp:  T = defined & cmp,  F = defined & ~cmp
        not:           (T, F) -> (F, T)
        and:           T = Tl & Tr,        F = Fl | (Tl & Fr)
        or:            T = Tl | (Fl & Tr), F = Fl & Fr

  * equality (`=`, `!=`) over plain operands (var / literal) is TOTAL:
    null or cross-type operands are simply unequal (no error) — so
    `missing != 'y'` is TRUE.  Equality over a compound side (an
    arithmetic expression) inherits that side's error semantics.
  * booleans are their own type: `retain = 1` is false even when
    retain is true (Erlang term inequality in the reference).

Columns are dual-typed: each var extracts to a float lane (NaN = not a
number/undefined) and a dictionary-encoded id lane (-1 = not a
string/bool; bools get reserved ids).  Comparisons pick lanes by
operand type.  Unsupported nodes (function calls, CASE, bare vars in
boolean position) make ``compile_where`` return None and the caller
falls back to the interpreter.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .runtime import lookup_var

# reserved string-lane ids for booleans ('\x00' cannot occur in MQTT
# UTF-8 strings, so these keys cannot collide with real payloads)
_TRUE_KEY = "\x00true"
_FALSE_KEY = "\x00false"


class _Unsupported(Exception):
    pass


class PredicateProgram:
    """A compiled WHERE: collect var columns, evaluate batched."""

    def __init__(self, where: tuple, var_paths: List[Tuple[str, ...]]):
        self.where = where
        self.var_paths = var_paths
        self._jit = None

    # ---------------------------------------------------- extraction

    def extract_columns(
        self, envs: Sequence[Dict[str, Any]]
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, int]]:
        """Host side: pull each var path from each env into dual-typed
        columns; strings (and bools) dictionary-encoded per batch."""
        n = len(envs)
        sdict: Dict[str, int] = {_TRUE_KEY: 0, _FALSE_KEY: 1}
        num = {p: np.full(n, np.nan, np.float64) for p in self.var_paths}
        sid = {p: np.full(n, -1, np.int32) for p in self.var_paths}
        # a lookup ERROR (e.g. descending into a non-JSON payload) is
        # not the same as undefined: the interpreter errors when it
        # evaluates that var, making the WHERE false — tracked as a
        # third lane so total equality stays oracle-equal
        err = {p: np.zeros(n, bool) for p in self.var_paths}
        for i, env in enumerate(envs):
            for p in self.var_paths:
                try:
                    v = lookup_var(env, p)
                except Exception:
                    err[p][i] = True
                    continue
                if isinstance(v, bool):
                    sid[p][i] = sdict[_TRUE_KEY if v else _FALSE_KEY]
                elif isinstance(v, (int, float)):
                    num[p][i] = v
                elif isinstance(v, str):
                    key = str(v)
                    if key not in sdict:
                        sdict[key] = len(sdict)
                    sid[p][i] = sdict[key]
        cols = {}
        for p in self.var_paths:
            cols["n:" + "/".join(p)] = num[p]
            cols["s:" + "/".join(p)] = sid[p]
            cols["e:" + "/".join(p)] = err[p]
        return cols, sdict

    # ---------------------------------------------------- evaluation

    def eval_batch(
        self, envs: Sequence[Dict[str, Any]], use_jax: bool = False
    ) -> np.ndarray:
        cols, sdict = self.extract_columns(envs)
        lit_ids = _literal_ids(self.where, sdict)
        if use_jax and self._f32_safe(cols):
            import jax

            if self._jit is None:
                import jax.numpy as jnp

                def fn(cols, lit_ids):
                    t, _ = _eval(self.where, cols, lit_ids, jnp)
                    return t

                self._jit = jax.jit(fn)
            return np.asarray(self._jit(cols, lit_ids))
        t, _ = _eval(self.where, cols, lit_ids, np)
        return np.asarray(t)

    def _f32_safe(self, cols: Dict[str, np.ndarray]) -> bool:
        """The device path computes in float32 (jax default / TPU
        native); use it only when every numeric value round-trips
        exactly AND the WHERE performs no arithmetic — an arithmetic
        RESULT can lose precision even when every input round-trips
        (16777216+1 == 16777216 in f32), so any arith stays on the
        float64 host path.  Millisecond timestamps are the canonical
        input offender."""
        if _has_arith(self.where):
            return False
        lits: List[float] = []
        _num_literals(self.where, lits)
        for v in lits:
            if float(np.float32(v)) != v:
                return False
        for name, a in cols.items():
            if name.startswith("n:"):
                finite = a[np.isfinite(a)]
                if not (finite == finite.astype(np.float32)).all():
                    return False
        return True


def _has_arith(expr: tuple) -> bool:
    kind = expr[0]
    if kind == "neg":
        return True
    if kind == "op":
        if expr[1] in ("+", "-", "*", "/", "div", "mod"):
            return True
        return _has_arith(expr[2]) or _has_arith(expr[3])
    if kind == "not":
        return _has_arith(expr[1])
    if kind == "in":
        return _has_arith(expr[1]) or any(_has_arith(e) for e in expr[2])
    return False


def _num_literals(expr: tuple, out: List[float]) -> None:
    kind = expr[0]
    if kind == "lit" and isinstance(expr[1], (int, float)) and not isinstance(
        expr[1], bool
    ):
        out.append(float(expr[1]))
    elif kind == "op":
        _num_literals(expr[2], out)
        _num_literals(expr[3], out)
    elif kind in ("not", "neg"):
        _num_literals(expr[1], out)
    elif kind == "in":
        _num_literals(expr[1], out)
        for e in expr[2]:
            _num_literals(e, out)


def _string_literals(expr: tuple, out: Set[str]) -> None:
    kind = expr[0]
    if kind == "lit" and isinstance(expr[1], str):
        out.add(expr[1])
    elif kind == "op":
        _string_literals(expr[2], out)
        _string_literals(expr[3], out)
    elif kind in ("not", "neg"):
        _string_literals(expr[1], out)
    elif kind == "in":
        _string_literals(expr[1], out)
        for e in expr[2]:
            _string_literals(e, out)


def _literal_ids(where: tuple, sdict: Dict[str, int]) -> Dict[str, int]:
    """Map string literals to batch-dict ids (-2 = absent from batch:
    matches nothing, distinct from -1 'not a string')."""
    lits: Set[str] = set()
    _string_literals(where, lits)
    return {s: sdict.get(s, -2) for s in lits}


def _collect_vars(expr: tuple, out: List[Tuple[str, ...]]) -> None:
    kind = expr[0]
    if kind == "var":
        if expr[1] not in out:
            out.append(expr[1])
    elif kind == "op":
        _collect_vars(expr[2], out)
        _collect_vars(expr[3], out)
    elif kind in ("not", "neg"):
        _collect_vars(expr[1], out)
    elif kind == "in":
        _collect_vars(expr[1], out)
        for e in expr[2]:
            _collect_vars(e, out)
    elif kind in ("call", "case"):
        raise _Unsupported(kind)


def compile_where(where: Optional[tuple]) -> Optional[PredicateProgram]:
    """Compile if every node is in the supported subset, else None."""
    if where is None:
        return None
    try:
        paths: List[Tuple[str, ...]] = []
        _collect_vars(where, paths)
        _check_bool(where)
    except _Unsupported:
        return None
    return PredicateProgram(where, paths)


def _check_bool(expr: tuple) -> None:
    """Validate a boolean-position node."""
    kind = expr[0]
    if kind == "lit" and isinstance(expr[1], bool):
        return
    if kind == "not":
        return _check_bool(expr[1])
    if kind == "in":
        lt = _check_val(expr[1])
        for e in expr[2]:
            et = _check_val(e)
            if "bool" in (lt, et):
                raise _Unsupported("bool in IN")
            if lt != "var" and et != "var" and et != lt:
                raise _Unsupported("mixed IN list")
        return
    if kind == "op":
        sym = expr[1]
        if sym in ("and", "or"):
            _check_bool(expr[2])
            _check_bool(expr[3])
            return
        if sym in ("=", "!=", ">", "<", ">=", "<="):
            lt, rt = _check_val(expr[2]), _check_val(expr[3])
            if "bool" in (lt, rt):
                raise _Unsupported("bool compare")
            if lt == "str" and rt == "str":
                raise _Unsupported("str-str compare is constant")
            if "str" in (lt, rt):
                other = rt if lt == "str" else lt
                if other != "var":
                    raise _Unsupported("str vs num compare")
                if sym not in ("=", "!="):
                    raise _Unsupported("string ordering")
            return
    raise _Unsupported(f"{kind} at boolean position")


def _check_val(expr: tuple) -> str:
    """Validate a value-position node -> 'num' | 'str' | 'bool' |
    'var' (dual-typed) | 'expr' (compound numeric)."""
    kind = expr[0]
    if kind == "lit":
        v = expr[1]
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, (int, float)):
            return "num"
        if isinstance(v, str):
            return "str"
        raise _Unsupported(f"literal {v!r}")
    if kind == "var":
        return "var"
    if kind == "neg":
        t = _check_val(expr[1])
        if t not in ("num", "var", "expr"):
            raise _Unsupported("neg of non-number")
        return "expr"
    if kind == "op" and expr[1] in ("+", "-", "*", "/", "div", "mod"):
        for sub in (expr[2], expr[3]):
            if _check_val(sub) not in ("num", "var", "expr"):
                raise _Unsupported("arith on non-numbers")
        return "expr"
    raise _Unsupported(kind)


def _eval(expr: tuple, cols, lit_ids, xp):
    """Boolean-position evaluation -> (T, F) masks."""
    kind = expr[0]
    if kind == "op" and expr[1] in ("and", "or"):
        tl, fl = _eval(expr[2], cols, lit_ids, xp)
        tr, fr = _eval(expr[3], cols, lit_ids, xp)
        if expr[1] == "and":
            return tl & tr, fl | (tl & fr)
        return tl | (fl & tr), fl & fr
    if kind == "not":
        t, f = _eval(expr[1], cols, lit_ids, xp)
        return f, t
    if kind == "lit":  # bool literal (validated)
        n = _batch_len(cols)
        full = xp.full(n, bool(expr[1]))
        return full, ~full
    if kind == "in":
        ts = fs = None
        for e in expr[2]:
            t, f = _eval(("op", "=", expr[1], e), cols, lit_ids, xp)
            ts = t if ts is None else (ts | (fs & t))
            fs = f if fs is None else (fs & f)
        return ts, fs
    if kind == "op":
        return _eval_cmp(expr, cols, lit_ids, xp)
    raise AssertionError(f"non-boolean node at boolean position: {kind}")


def _is_simple(expr: tuple) -> bool:
    return expr[0] in ("lit", "var")


def _eval_cmp(expr: tuple, cols, lit_ids, xp):
    sym, le, re_ = expr[1], expr[2], expr[3]
    lstr = le[0] == "lit" and isinstance(le[1], str)
    rstr = re_[0] == "lit" and isinstance(re_[1], str)
    if lstr or rstr:
        # string-literal equality against a var's id lane; TOTAL except
        # when the var lookup itself ERRORED (interpreter: WHERE false)
        lit, var = (le, re_) if lstr else (re_, le)
        ids = cols["s:" + "/".join(var[1])]
        erv = cols["e:" + "/".join(var[1])]
        lid = lit_ids[lit[1]]
        eq = ~erv & (ids == lid)
        ne = ~erv & (ids != lid)
        return (eq, ne) if sym == "=" else (ne, eq)

    if sym in ("=", "!="):
        lv, ld = _num_eval_pair(le, cols, lit_ids, xp)
        rv, rd = _num_eval_pair(re_, cols, lit_ids, xp)
        eq = ld & rd & (lv == rv)
        if le[0] == "var" and re_[0] == "var":
            # var-var equality also matches on the id lanes
            li = cols["s:" + "/".join(le[1])]
            ri = cols["s:" + "/".join(re_[1])]
            eq = eq | ((li >= 0) & (li == ri))
        # equality itself is total; but a lookup ERROR on a simple var
        # (vs merely undefined) poisons the row, and a COMPOUND side
        # contributes its own error semantics (sub-expression may fail)
        ok = None
        for side in (le, re_):
            if side[0] == "var":
                e = cols["e:" + "/".join(side[1])]
                ok = ~e if ok is None else (ok & ~e)
        if ok is None:
            ok = xp.full(_batch_len(cols), True)
        cd = None
        for side, d in ((le, ld), (re_, rd)):
            if not _is_simple(side):
                cd = d if cd is None else (cd & d)
        if cd is None:
            return (
                (eq & ok, ~eq & ok) if sym == "=" else (~eq & ok, eq & ok)
            )
        return (
            (eq & ok, cd & ~eq & ok)
            if sym == "="
            else (cd & ~eq & ok, eq & ok)
        )

    # ordering: error semantics
    lv, ld = _num_eval_pair(le, cols, lit_ids, xp)
    rv, rd = _num_eval_pair(re_, cols, lit_ids, xp)
    d = ld & rd
    cmp = {
        ">": lv > rv,
        "<": lv < rv,
        ">=": lv >= rv,
        "<=": lv <= rv,
    }[sym]
    return d & cmp, d & ~cmp


def _num_eval_pair(expr: tuple, cols, lit_ids, xp):
    """Numeric (value, defined) evaluation."""
    kind = expr[0]
    if kind == "lit":
        n = _batch_len(cols)
        dt = np.float64 if xp is np else np.float32
        v = xp.full(n, float(expr[1]), dt)
        return v, xp.full(n, True)
    if kind == "var":
        v = cols["n:" + "/".join(expr[1])]
        return v, ~xp.isnan(v)
    if kind == "neg":
        v, d = _num_eval_pair(expr[1], cols, lit_ids, xp)
        return -v, d
    if kind == "op":
        sym = expr[1]
        lv, ld = _num_eval_pair(expr[2], cols, lit_ids, xp)
        rv, rd = _num_eval_pair(expr[3], cols, lit_ids, xp)
        d = ld & rd
        if sym == "+":
            return lv + rv, d
        if sym == "-":
            return lv - rv, d
        if sym == "*":
            return lv * rv, d
        if sym == "/":
            ok = rv != 0
            return xp.where(ok, lv / xp.where(ok, rv, 1), 0), d & ok
        # div/mod: the interpreter truncates BOTH operands to int
        # first (int(a) // int(b), int(a) % int(b)), then floor-divides
        ta = xp.trunc(lv)
        tb = xp.trunc(rv)
        ok = tb != 0
        safe = xp.where(ok, tb, 1)
        q = xp.floor(ta / safe)
        if sym == "div":
            return q, d & ok
        return ta - q * safe, d & ok
    raise AssertionError(f"bad numeric node {kind}")


def _batch_len(cols) -> int:
    return next(iter(cols.values())).shape[0]


# ===================================================================
# Registry-wide lowering: the rules x window matrix program
#
# The PredicateProgram above vectorizes ONE rule over a batch of envs;
# a broker with thousands of rules still pays a Python step per rule
# per window.  The lowering below goes the rest of the way: each
# rule's WHERE compiles into a LINEAR instruction row over a SHARED
# column space (`rules/columns.py` extracts the window once), and the
# whole registry stacks into opcode/operand matrices that
# `ops.match_kernel.rules_eval_host` / `rules_eval_batch` evaluate as
# one rules x window boolean matrix — the `decide_batch` discipline
# applied to the rule engine (ROADMAP "compile rule-engine SQL
# predicates into the batched kernel").
#
# Register machine: step s writes register s.  Numeric registers are
# (value, defined) pairs; boolean registers are the same (T, F)
# "provably true / provably false without error" pairs the
# short-circuit algebra above uses, so error semantics stay
# bit-identical to the interpreter.  Column planes per referenced
# path (see WindowColumns): ``num`` (float64, NaN = not a number),
# ``sid`` (int32 per-window string RANK, order-preserving, so string
# ordering comparisons lower too; -1 = not a string, -2/-3 = bool
# true/false), ``err`` (lookup raised), ``prs`` (lookup succeeded and
# value is not null).
# ===================================================================

R_NOP = 0
R_NLOAD = 1   # a0=plane           -> (num[p], ~isnan)
R_NLIT = 2    # litn[r,s]          -> (lit, True)
R_NNEG = 3    # a0=reg
R_NADD = 4    # a0,a1=regs
R_NSUB = 5
R_NMUL = 6
R_NDIV = 7    # defined &= rhs != 0
R_NIDV = 8    # trunc both, floor-divide (interpreter div)
R_NMOD = 9
R_BLIT = 10   # a0 = 0/1
R_BNOT = 11   # a0=reg             -> (F, T)
R_BAND = 12   # a0,a1=regs         -> (Tl&Tr, Fl|(Tl&Fr))
R_BOR = 13    # a0,a1=regs         -> (Tl|(Fl&Tr), Fl&Fr)
R_CGT = 14    # a0,a1=num regs; a2,a3=string planes (-1: not a bare
R_CLT = 15    #   var) — rows where BOTH sides are strings compare by
R_CGE = 16    #   per-window rank (interpreter string ordering)
R_CLE = 17
R_EQVV = 18   # a0=plane p, a1=plane q, a2=negate
R_EQVL = 19   # a0=plane p, numeric literal in litn, a2=negate
R_EQSL = 20   # a0=plane p, a1=string-literal index, a2=negate
R_EQC = 21    # a0,a1=num regs; a2=flags(neg|lcomp<<1|rcomp<<2);
              #   a3=simple-var err plane for totality (-1: none)
R_PRES = 22   # a0=plane p, a2=negate (negate -> is_null)

# rows deeper than this fall back to the interpreter (bounds the
# stacked register file: S x R x W planes)
MAX_STEPS = 48

# presence-check calls that lower onto the prs/err planes
_PRESENCE_FUNCS = {"is_null": 1, "is_not_null": 0}


class LoweredRule:
    """One rule's linear program over its LOCAL path/literal spaces
    (the stacker remaps to the registry-global spaces)."""

    __slots__ = ("steps", "paths", "lit_strings", "has_arith")

    def __init__(self) -> None:
        # (op, a0, a1, a2, a3, litn)
        self.steps: List[Tuple[int, int, int, int, int, float]] = []
        self.paths: List[Tuple[str, ...]] = []
        self.lit_strings: List[str] = []
        self.has_arith = False

    # ------------------------------------------------------- emit

    def _emit(self, op, a0=-1, a1=-1, a2=-1, a3=-1, litn=0.0) -> int:
        if len(self.steps) >= MAX_STEPS:
            raise _Unsupported("program too long")
        self.steps.append((op, a0, a1, a2, a3, float(litn)))
        return len(self.steps) - 1

    def _plane(self, path: Tuple[str, ...]) -> int:
        if path not in self.paths:
            self.paths.append(path)
        return self.paths.index(path)

    def _slit(self, s: str) -> int:
        if s not in self.lit_strings:
            self.lit_strings.append(s)
        return self.lit_strings.index(s)

    # ------------------------------------------------- bool position

    def lower_bool(self, expr: tuple) -> int:
        kind = expr[0]
        if kind == "lit" and isinstance(expr[1], bool):
            return self._emit(R_BLIT, 1 if expr[1] else 0)
        if kind == "not":
            return self._emit(R_BNOT, self.lower_bool(expr[1]))
        if kind == "in":
            lt = _check_val(expr[1])
            reg = None
            for e in expr[2]:
                et = _check_val(e)
                if "bool" in (lt, et):
                    raise _Unsupported("bool in IN")
                if lt != "var" and et != "var" and et != lt:
                    raise _Unsupported("mixed IN list")
                r = self.lower_cmp("=", expr[1], e)
                reg = r if reg is None else self._emit(R_BOR, reg, r)
            if reg is None:
                raise _Unsupported("empty IN")
            return reg
        if kind == "op":
            sym = expr[1]
            if sym == "and":
                return self._emit(
                    R_BAND,
                    self.lower_bool(expr[2]),
                    self.lower_bool(expr[3]),
                )
            if sym == "or":
                return self._emit(
                    R_BOR,
                    self.lower_bool(expr[2]),
                    self.lower_bool(expr[3]),
                )
            if sym in ("=", "!=", ">", "<", ">=", "<="):
                return self.lower_cmp(sym, expr[2], expr[3])
        if kind == "call":
            neg = _PRESENCE_FUNCS.get(expr[1])
            if (
                neg is not None
                and len(expr[2]) == 1
                and expr[2][0][0] == "var"
            ):
                p = self._plane(expr[2][0][1])
                return self._emit(R_PRES, p, -1, neg)
        raise _Unsupported(f"{kind} at boolean position")

    # ------------------------------------------------- comparisons

    def lower_cmp(self, sym: str, le: tuple, re_: tuple) -> int:
        lt, rt = _check_val(le), _check_val(re_)
        if "bool" in (lt, rt):
            raise _Unsupported("bool compare")
        if "str" in (lt, rt):
            if lt == "str" and rt == "str":
                if sym in ("=", "!="):
                    # constant-fold literal equality (IN lists build
                    # these); _sql_eq semantics on two str literals
                    eq = le[1] == re_[1]
                    if sym == "!=":
                        eq = not eq
                    return self._emit(R_BLIT, 1 if eq else 0)
                raise _Unsupported("str-str compare is constant")
            other = rt if lt == "str" else lt
            if other != "var":
                raise _Unsupported("str vs num compare")
            if sym not in ("=", "!="):
                raise _Unsupported("string ordering vs literal")
            lit, var = (le, re_) if lt == "str" else (re_, le)
            return self._emit(
                R_EQSL,
                self._plane(var[1]),
                self._slit(lit[1]),
                1 if sym == "!=" else 0,
            )
        if sym in ("=", "!="):
            neg = 1 if sym == "!=" else 0
            if lt == "var" and rt == "var":
                return self._emit(
                    R_EQVV, self._plane(le[1]), self._plane(re_[1]), neg
                )
            if lt == "var" and rt == "num":
                return self._emit(
                    R_EQVL, self._plane(le[1]), -1, neg, -1, re_[1]
                )
            if lt == "num" and rt == "var":
                return self._emit(
                    R_EQVL, self._plane(re_[1]), -1, neg, -1, le[1]
                )
            if lt == "num" and rt == "num":
                eq = float(le[1]) == float(re_[1])
                if neg:
                    eq = not eq
                return self._emit(R_BLIT, 1 if eq else 0)
            # a compound side carries its own error semantics
            a = self.lower_num(le)
            b = self.lower_num(re_)
            flags = neg
            if not _is_simple(le):
                flags |= 2
            if not _is_simple(re_):
                flags |= 4
            okp = -1
            if le[0] == "var":
                okp = self._plane(le[1])
            elif re_[0] == "var":
                okp = self._plane(re_[1])
            return self._emit(R_EQC, a, b, flags, okp)
        # ordering: numeric via the regs, string via per-window ranks
        # when BOTH sides are bare vars (rank order == lex order)
        a = self.lower_num(le)
        b = self.lower_num(re_)
        sp = self._plane(le[1]) if le[0] == "var" else -1
        sq = self._plane(re_[1]) if re_[0] == "var" else -1
        op = {">": R_CGT, "<": R_CLT, ">=": R_CGE, "<=": R_CLE}[sym]
        return self._emit(op, a, b, sp, sq)

    # --------------------------------------------------- value (num)

    def lower_num(self, expr: tuple) -> int:
        kind = expr[0]
        if kind == "lit":
            v = expr[1]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise _Unsupported(f"numeric literal {v!r}")
            return self._emit(R_NLIT, -1, -1, -1, -1, v)
        if kind == "var":
            return self._emit(R_NLOAD, self._plane(expr[1]))
        if kind == "neg":
            if _check_val(expr[1]) not in ("num", "var", "expr"):
                raise _Unsupported("neg of non-number")
            self.has_arith = True
            return self._emit(R_NNEG, self.lower_num(expr[1]))
        if kind == "op" and expr[1] in ("+", "-", "*", "/", "div", "mod"):
            for sub in (expr[2], expr[3]):
                if _check_val(sub) not in ("num", "var", "expr"):
                    raise _Unsupported("arith on non-numbers")
            if expr[1] == "+" and _could_be_str(expr[2]) and (
                _could_be_str(expr[3])
            ):
                # interpreter '+' CONCATENATES two runtime strings;
                # the numeric lanes cannot — degrade this rule.
                # (string + number errors on both paths, so a single
                # could-be-string side stays lowerable.)
                raise _Unsupported("possible string concat")
            self.has_arith = True
            op = {
                "+": R_NADD, "-": R_NSUB, "*": R_NMUL,
                "/": R_NDIV, "div": R_NIDV, "mod": R_NMOD,
            }[expr[1]]
            return self._emit(
                op, self.lower_num(expr[2]), self.lower_num(expr[3])
            )
        raise _Unsupported(kind)


def _could_be_str(expr: tuple) -> bool:
    """Can this value expression produce a STRING at runtime?  Bare
    vars are dual-typed; a ``+`` of two could-be-strings can
    concatenate; every other arith shape errors on strings (making
    its result numeric-or-error on both paths)."""
    if expr[0] == "var":
        return True
    if expr[0] == "op" and expr[1] == "+":
        return _could_be_str(expr[2]) and _could_be_str(expr[3])
    return False


def lower_where(where: Optional[tuple]) -> Optional[LoweredRule]:
    """Lower one WHERE into a linear program row, or None when any
    node is outside the lowerable subset (regex/UDF-shaped calls,
    CASE, bare vars in boolean position, over-long programs) — the
    caller then degrades that RULE, not the window, to the
    interpreter."""
    prog = LoweredRule()
    if where is None:
        prog._emit(R_BLIT, 1)  # no WHERE: every routed message passes
        return prog
    try:
        prog.lower_bool(where)
    except _Unsupported:
        return None
    return prog


class StackedRules:
    """The whole registry's lowerable rules as one stacked program:
    opcode/operand matrices ``[R, S]`` over a shared plane space, plus
    the fallback set.  Built once per ``rules_rev`` (registry churn
    invalidates); `ops.match_kernel.rules_eval_host`/`rules_eval_batch`
    evaluate it against a `WindowColumns` extraction.

    Identical programs DEDUP to one matrix row (``row_of`` maps every
    rule id to its shared row): a fleet registry of thousands of
    per-device rules differing only in topic filter — the IoT-pipeline
    shape — evaluates its WHERE once per distinct program, not once
    per rule, while per-rule matched/passed counters stay exact (the
    pair bookkeeping is per rule, only the boolean matrix is
    shared)."""

    __slots__ = (
        "row_of", "fallback", "paths", "lit_strings",
        "code", "a0", "a1", "a2", "a3", "litn", "last",
        "has_arith", "n_steps", "f32_lits_safe", "n_lowered",
    )

    def __init__(self, lowered: List[Tuple[str, LoweredRule]],
                 fallback: List[str]) -> None:
        self.fallback = fallback
        self.n_lowered = len(lowered)
        paths: List[Tuple[str, ...]] = []
        path_ix: Dict[Tuple[str, ...], int] = {}
        lits: List[str] = []
        lit_ix: Dict[str, int] = {}
        self.has_arith = any(p.has_arith for _, p in lowered)
        n_steps = max((len(p.steps) for _, p in lowered), default=1)
        self.n_steps = n_steps
        # which operand slots hold a plane index (per opcode) — the
        # stacker remaps those from rule-local to global planes
        plane_slots = {
            R_NLOAD: (0,), R_EQVV: (0, 1), R_EQVL: (0,),
            R_EQSL: (0,), R_PRES: (0,),
            R_CGT: (2, 3), R_CLT: (2, 3), R_CGE: (2, 3), R_CLE: (2, 3),
            R_EQC: (3,),
        }
        row_of: Dict[str, int] = {}
        uniq: Dict[Tuple, int] = {}
        programs: List[Tuple] = []
        for rid, prog in lowered:
            pmap = []
            for p in prog.paths:
                if p not in path_ix:
                    path_ix[p] = len(paths)
                    paths.append(p)
                pmap.append(path_ix[p])
            lmap = []
            for s in prog.lit_strings:
                if s not in lit_ix:
                    lit_ix[s] = len(lits)
                    lits.append(s)
                lmap.append(lit_ix[s])
            remapped = []
            for op, b0, b1, b2, b3, lv in prog.steps:
                args = [b0, b1, b2, b3]
                for slot in plane_slots.get(op, ()):
                    if args[slot] >= 0:
                        args[slot] = pmap[args[slot]]
                if op == R_EQSL and args[1] >= 0:
                    args[1] = lmap[args[1]]
                remapped.append((op, *args, lv))
            key = tuple(remapped)
            row = uniq.get(key)
            if row is None:
                row = uniq[key] = len(programs)
                programs.append(key)
            row_of[rid] = row
        self.row_of = row_of
        n_rows = max(len(programs), 0)
        code = np.zeros((n_rows, n_steps), np.int32)
        a0 = np.full((n_rows, n_steps), -1, np.int32)
        a1 = np.full((n_rows, n_steps), -1, np.int32)
        a2 = np.full((n_rows, n_steps), -1, np.int32)
        a3 = np.full((n_rows, n_steps), -1, np.int32)
        litn = np.zeros((n_rows, n_steps), np.float64)
        last = np.zeros(n_rows, np.int32)
        for r, steps in enumerate(programs):
            for s, (op, c0, c1, c2, c3, lv) in enumerate(steps):
                code[r, s] = op
                a0[r, s], a1[r, s] = c0, c1
                a2[r, s], a3[r, s] = c2, c3
                litn[r, s] = lv
            last[r] = len(steps) - 1
        self.paths = paths
        self.lit_strings = lits
        self.code, self.litn, self.last = code, litn, last
        self.a0, self.a1, self.a2, self.a3 = a0, a1, a2, a3
        # numeric literals that survive float32 (device path gate,
        # same rule as PredicateProgram._f32_safe)
        self.f32_lits_safe = all(
            float(np.float32(v)) == v for v in litn.ravel().tolist()
        )

    @property
    def n_rules(self) -> int:
        """Distinct program rows (rules sharing a program share a
        row; `n_lowered` counts the rules themselves)."""
        return self.code.shape[0]


def build_stack(
    rules: Sequence[Tuple[str, Optional[tuple]]]
) -> StackedRules:
    """Stack every lowerable ``(rule_id, where)``; the rest land in
    ``fallback`` (degrade per RULE, never per window)."""
    lowered: List[Tuple[str, LoweredRule]] = []
    fallback: List[str] = []
    for rid, where in rules:
        prog = lower_where(where)
        if prog is None:
            fallback.append(rid)
        else:
            lowered.append((rid, prog))
    return StackedRules(lowered, fallback)
