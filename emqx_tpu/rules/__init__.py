"""SQL rule engine.

The reference's `emqx_rule_engine` (/root/reference/apps/
emqx_rule_engine/src/): rules are SQL statements whose FROM topics are
matched per message through the shared topic index
(emqx_rule_engine.erl:226-231) and whose WHERE/SELECT run per match
(emqx_rule_runtime.erl:60-100).  Here FROM filters are compiled into
the *same* match-engine automaton as subscriptions (distinct fid
class), so rule matching rides the batched device step; WHERE
predicates additionally compile to a batched column program
(`predicate.py`) with the interpreter as oracle.
"""

from .engine import Rule, RuleEngine  # noqa: F401
from .sql import parse_sql, SqlError  # noqa: F401
