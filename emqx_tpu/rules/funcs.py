"""Rule SQL function library.

A working subset of the reference's 1.3 kLoC stdlib (`emqx_rule_funcs`,
/root/reference/apps/emqx_rule_engine/src/emqx_rule_funcs.erl),
grouped the same way: math, string, map/array, type conversion, time,
hashing, compression-free encoding.  All functions are total over
``None`` where the reference is (undefined propagates as failure ->
the rule's WHERE treats it as false).
"""

from __future__ import annotations

import base64
import fnmatch
import hashlib
import json
import math
import time
import uuid
from typing import Any, Callable, Dict, List, Optional


def _num(x: Any) -> float:
    if isinstance(x, bool) or not isinstance(x, (int, float)):
        raise TypeError(f"not a number: {x!r}")
    return x


def _like(s: Any, pattern: Any) -> bool:
    """SQL LIKE: % = any run, _ = one char."""
    if not isinstance(s, str) or not isinstance(pattern, str):
        return False
    out = []
    for ch in pattern:
        if ch == "%":
            out.append("*")
        elif ch == "_":
            out.append("?")
        elif ch in "*?[":  # neutralize fnmatch metacharacters
            out.append("[" + ch + "]")
        else:
            out.append(ch)
    return fnmatch.fnmatchcase(s, "".join(out))


FUNCS: Dict[str, Callable[..., Any]] = {}


def _register(name: str):
    def deco(fn):
        FUNCS[name] = fn
        return fn

    return deco


# ------------------------------------------------------------------ math

for _name, _fn in {
    "abs": abs,
    "ceil": math.ceil,
    "floor": math.floor,
    "sqrt": math.sqrt,
    "exp": math.exp,
    "log": math.log,
    "log10": math.log10,
    "log2": math.log2,
    "sin": math.sin,
    "cos": math.cos,
    "tan": math.tan,
}.items():
    FUNCS[_name] = (lambda f: lambda x: f(_num(x)))(_fn)

FUNCS["round"] = lambda x, n=0: round(_num(x), int(n)) if n else round(_num(x))
FUNCS["power"] = lambda x, y: math.pow(_num(x), _num(y))
FUNCS["pow"] = FUNCS["power"]
FUNCS["fmod"] = lambda x, y: math.fmod(_num(x), _num(y))
FUNCS["random"] = lambda: __import__("random").random()
FUNCS["max"] = lambda *a: max(a)
FUNCS["min"] = lambda *a: min(a)


# ---------------------------------------------------------------- strings


@_register("lower")
def _lower(s):
    return str(s).lower()


@_register("upper")
def _upper(s):
    return str(s).upper()


@_register("trim")
def _trim(s):
    return str(s).strip()


@_register("ltrim")
def _ltrim(s):
    return str(s).lstrip()


@_register("rtrim")
def _rtrim(s):
    return str(s).rstrip()


@_register("reverse")
def _reverse(s):
    return str(s)[::-1]


@_register("strlen")
def _strlen(s):
    return len(str(s))


@_register("substr")
def _substr(s, start, length=None):
    s = str(s)
    start = int(start)
    return s[start:] if length is None else s[start : start + int(length)]


@_register("concat")
def _concat(*parts):
    return "".join(str(p) for p in parts)


@_register("split")
def _split(s, sep=" "):
    return str(s).split(str(sep))


@_register("tokens")
def _tokens(s, sep=" "):
    return [t for t in str(s).split(str(sep)) if t]


@_register("replace")
def _replace(s, old, new):
    return str(s).replace(str(old), str(new))


@_register("regex_match")
def _regex_match(s, pattern):
    import re

    return re.search(str(pattern), str(s)) is not None


@_register("regex_replace")
def _regex_replace(s, pattern, repl):
    import re

    return re.sub(str(pattern), str(repl), str(s))


@_register("ascii")
def _ascii(ch):
    return ord(str(ch)[0])


@_register("find")
def _find(s, sub):
    s = str(s)
    i = s.find(str(sub))
    return s[i:] if i >= 0 else ""


@_register("pad")
def _pad(s, n, side="trailing", char=" "):
    s, n, char = str(s), int(n), str(char)
    if side == "leading":
        return s.rjust(n, char)
    if side == "both":
        total = max(n - len(s), 0)
        left = total // 2
        return char * left + s + char * (total - left)
    return s.ljust(n, char)


@_register("sprintf")
def _sprintf(fmt, *args):
    return str(fmt).replace("~p", "%s").replace("~s", "%s") % args


FUNCS["like"] = _like


# ---------------------------------------------------------- maps / arrays


@_register("map_get")
def _map_get(key, m, default=None):
    if isinstance(m, dict):
        return m.get(str(key), default)
    return default


@_register("map_put")
def _map_put(key, val, m):
    out = dict(m) if isinstance(m, dict) else {}
    out[str(key)] = val
    return out


@_register("mget")
def _mget(key, m, default=None):
    return _map_get(key, m, default)


@_register("contains")
def _contains(item, arr):
    return isinstance(arr, (list, tuple)) and item in arr


@_register("nth")
def _nth(n, arr):
    n = int(n)
    if isinstance(arr, (list, tuple)) and 1 <= n <= len(arr):
        return arr[n - 1]
    return None


@_register("length")
def _length(x):
    return len(x)


@_register("sublist")
def _sublist(*args):
    if len(args) == 2:
        n, arr = args
        return list(arr[: int(n)])
    start, n, arr = args
    return list(arr[int(start) - 1 : int(start) - 1 + int(n)])


@_register("first")
def _first(arr):
    return arr[0] if arr else None


@_register("last")
def _last(arr):
    return arr[-1] if arr else None


# -------------------------------------------------------- type conversion


@_register("str")
def _str(x):
    if isinstance(x, bytes):
        return x.decode("utf-8", "replace")
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, float) and x.is_integer():
        return str(int(x))
    if isinstance(x, (dict, list)):
        return json.dumps(x)
    return str(x)


@_register("int")
def _int(x):
    if isinstance(x, str):
        return int(float(x)) if "." in x else int(x)
    return int(x)


@_register("float")
def _float(x):
    return float(x)


@_register("bool")
def _bool(x):
    if isinstance(x, bool):
        return x
    if x in ("true", 1):
        return True
    if x in ("false", 0):
        return False
    raise TypeError(f"not a bool: {x!r}")


@_register("is_null")
def _is_null(x):
    return x is None


@_register("is_not_null")
def _is_not_null(x):
    return x is not None


@_register("is_num")
def _is_num(x):
    return isinstance(x, (int, float)) and not isinstance(x, bool)


@_register("is_int")
def _is_int(x):
    return isinstance(x, int) and not isinstance(x, bool)


@_register("is_float")
def _is_float(x):
    return isinstance(x, float)


@_register("is_str")
def _is_str(x):
    return isinstance(x, str)


@_register("is_bool")
def _is_bool(x):
    return isinstance(x, bool)


@_register("is_map")
def _is_map(x):
    return isinstance(x, dict)


@_register("is_array")
def _is_array(x):
    return isinstance(x, (list, tuple))


# -------------------------------------------------------- json / encoding


@_register("schema_decode")
def _schema_decode(name, payload, message_type=None):
    """Decode a payload against a registered schema
    (emqx_rule_funcs:schema_decode — avro/protobuf/json by name)."""
    from ..schema_registry import global_registry

    if isinstance(payload, str):
        payload = payload.encode()
    return global_registry().decode(name, payload, message_type)


@_register("schema_encode")
def _schema_encode(name, value, message_type=None):
    from ..schema_registry import global_registry

    return global_registry().encode(name, value, message_type)


@_register("schema_check")
def _schema_check(name, payload):
    from ..schema_registry import global_registry

    if isinstance(payload, str):
        payload = payload.encode()
    return global_registry().check(name, payload)


@_register("json_decode")
def _json_decode(s):
    if isinstance(s, bytes):
        s = s.decode("utf-8")
    return json.loads(s)


@_register("json_encode")
def _json_encode(x):
    return json.dumps(x)


@_register("base64_encode")
def _b64e(x):
    if isinstance(x, str):
        x = x.encode("utf-8")
    return base64.b64encode(x).decode("ascii")


@_register("base64_decode")
def _b64d(s):
    return base64.b64decode(s)


@_register("bin2hexstr")
def _bin2hex(b):
    if isinstance(b, str):
        b = b.encode("utf-8")
    return b.hex()


@_register("hexstr2bin")
def _hex2bin(s):
    return bytes.fromhex(str(s))


# --------------------------------------------------------------- hashing


@_register("md5")
def _md5(x):
    return hashlib.md5(_as_bytes(x)).hexdigest()


@_register("sha")
def _sha(x):
    return hashlib.sha1(_as_bytes(x)).hexdigest()


@_register("sha256")
def _sha256(x):
    return hashlib.sha256(_as_bytes(x)).hexdigest()


def _as_bytes(x) -> bytes:
    return x if isinstance(x, bytes) else str(x).encode("utf-8")


# ------------------------------------------------------------------ time


@_register("now_timestamp")
def _now_timestamp(unit="second"):
    t = time.time()
    return int(t * {"second": 1, "millisecond": 1e3, "microsecond": 1e6}[unit])


@_register("timezone_to_second")
def _tz_to_s(tz):
    if tz in ("Z", "z"):
        return 0
    sign = -1 if tz.startswith("-") else 1
    hh, mm = tz.lstrip("+-").split(":")
    return sign * (int(hh) * 3600 + int(mm) * 60)


FUNCS["uuid_v4"] = lambda: str(uuid.uuid4())


# ------------------------------------------------------------ mqtt-domain


@_register("topic")
def _topic_join(*levels):
    return "/".join(str(x) for x in levels)
