"""Structured logging: JSON formatter + repeated-event throttling.

The `emqx_logger` / `emqx_log_throttler` roles
(/root/reference/apps/emqx/src/emqx_logger.erl JSON/structured
formatters, emqx_log_throttler.erl:62-105 per-event-window dedup):

  * `JsonFormatter` — one JSON object per line (ts, level, logger,
    msg, plus any ``extra`` fields), machine-shippable as-is.
  * `LogThrottler` — a logging.Filter that lets the FIRST event of a
    throttle key through per window and swallows the rest; at window
    roll it emits one summary line with the dropped count (the
    reference's "dropped N events" report).  Keyed on an explicit
    ``throttle`` extra when present, else on (logger, msg-template) —
    so hot-path repeats (auth failures, socket errors) cannot flood
    the log at line rate.
"""

from __future__ import annotations

import copy
import json
import logging
import time
from typing import Dict, Optional, Tuple


class JsonFormatter(logging.Formatter):
    """One JSON object per record, stable keys first."""

    _STD = {
        "name", "msg", "args", "levelname", "levelno", "pathname",
        "filename", "module", "exc_info", "exc_text", "stack_info",
        "lineno", "funcName", "created", "msecs", "relativeCreated",
        "thread", "threadName", "processName", "process",
        "taskName", "throttle",
    }

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        for k, v in record.__dict__.items():
            if k not in self._STD and not k.startswith("_"):
                try:
                    json.dumps(v)
                    out[k] = v
                except (TypeError, ValueError):
                    out[k] = repr(v)
        return json.dumps(out, separators=(",", ":"))


class LogThrottler(logging.Filter):
    """First-per-window pass-through with dropped-count summaries."""

    def __init__(self, window_s: float = 60.0,
                 max_keys: int = 4096,
                 handler: Optional[logging.Handler] = None) -> None:
        super().__init__()
        self.window_s = window_s
        self.max_keys = max_keys
        # the handler this filter is attached to; summary records are
        # emitted on it directly so the shared LogRecord instance other
        # handlers (e.g. the OTel log handler) see is never mutated
        self.handler = handler
        # key -> (window_start, dropped_count)
        self._seen: Dict[Tuple[str, str], Tuple[float, int]] = {}

    def _key(self, record: logging.LogRecord) -> Tuple[str, str]:
        tag = getattr(record, "throttle", None)
        if tag is not None:
            return (record.name, str(tag))
        return (record.name, str(record.msg))

    def filter(self, record: logging.LogRecord) -> bool:
        if getattr(record, "_throttle_summary", False):
            return True  # our own summary copy re-entering via handle()
        if record.levelno >= logging.ERROR:
            return True  # errors always pass (reference behavior)
        now = time.monotonic()
        key = self._key(record)
        entry = self._seen.get(key)
        if entry is None:
            if len(self._seen) >= self.max_keys:
                self._seen.clear()
            self._seen[key] = (now, 0)
            return True
        start, dropped = entry
        if now - start < self.window_s:
            self._seen[key] = (start, dropped + 1)
            return False
        # window rolled: emit, and summarize what was swallowed — on a
        # COPY, because this record instance is shared with every other
        # handler on the logger tree; mutating msg in place would make
        # their output depend on handler order
        self._seen[key] = (now, 0)
        if dropped:
            summary = copy.copy(record)
            summary.msg = (f"{record.getMessage()} (throttled: {dropped} "
                           f"similar events in the last "
                           f"{self.window_s:.0f}s)")
            summary.args = ()
            summary._throttle_summary = True
            if self.handler is not None:
                # handler-attached (configure() wiring): emit the copy
                # on OUR handler only; siblings see the plain original
                if summary.levelno >= self.handler.level:
                    self.handler.handle(summary)
                return False
            # logger-attached fallback (no handler bound): annotating a
            # copy is impossible — a filter cannot substitute the
            # record — so keep the legacy in-place annotation rather
            # than silently losing the dropped count
            record.msg = summary.msg
            record.args = ()
        return True


def configure(
    fmt: str = "text",
    level: str = "info",
    throttle_window_s: Optional[float] = None,
) -> None:
    """Apply the configured format/level/throttle to the emqx_tpu
    logger tree (the `log.*` config section).

    The throttler attaches to OUR handler, not the logger: Python
    applies logger-level filters only to records emitted on that exact
    logger, and nearly every log site uses a child
    (``emqx_tpu.<module>``) — records propagating up bypass logger
    filters but do pass handler filters."""
    root = logging.getLogger("emqx_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    # reconfiguration replaces our handler instead of stacking a new
    # one per configure() call (which would duplicate every line)
    for h in list(root.handlers):
        if getattr(h, "_emqx_tpu_handler", False):
            root.removeHandler(h)
    handler = logging.StreamHandler()
    handler._emqx_tpu_handler = True
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s"
        ))
    if throttle_window_s:
        handler.addFilter(LogThrottler(window_s=throttle_window_s,
                                       handler=handler))
    root.addHandler(handler)
    root.propagate = False
