"""Test-only runtime instrumentation shipped with the broker (so the
racesim harness and downstream users can import it without reaching
into the test tree).  Nothing in here runs in production paths."""
