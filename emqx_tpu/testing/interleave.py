"""Forced-interleaving sanitizer: adversarial task scheduling.

The static RACE8xx rules (tools/brokerlint/racerules.py) reason about
windows that open when an ``await`` yields the event loop.  This
module is the runtime counterpart: it wraps every task the loop
creates in a driver that intercepts each suspension point and — as
directed by a :class:`SchedulePolicy` — forces extra trips through
the ready queue before the task is allowed to park on its awaitable.
A race that needs "another task ran in the window between my check
and my act" stops being a one-in-a-million timing accident and
becomes a schedule the policy can hit deterministically (and, with
the same seed, hit again).

Three policy modes, same spirit as crashsim's crash-point
enumeration:

  * ``random``   — seeded coin flip at every yieldpoint; the workhorse
    for property suites (N seeds, same workload).
  * ``targeted`` — preempt only at sites whose name matches one of
    the given substrings (site names are ``<coro qualname>:<step>``
    or ``seam:<failpoint seam>``); everything else runs undisturbed.
  * ``script``   — an explicit 0/1 decision vector consumed in call
    order, 0 once exhausted: the building block for exhaustive
    small-schedule enumeration (see tools/racesim).

Every decision is recorded in ``policy.trace`` — the schedule — so
"same seed ⇒ same schedule" is a testable property and a failing
schedule can be replayed as a script.

Usage::

    policy = SchedulePolicy(mode="random", seed=7, prob=1.0)
    asyncio.run(drive(main(), policy))

``drive`` installs a task factory on the running loop (every task
spawned by the workload is instrumented too), runs the coroutine,
and restores the loop on exit.  ``failpoint_yieldpoints`` extends
coverage to the declared IO seams: inside the context every
``failpoints.evaluate_async`` call becomes a yieldpoint named
``seam:<name>``.
"""

from __future__ import annotations

import asyncio
import contextlib
import random
import types
from typing import Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "SchedulePolicy", "drive", "failpoint_yieldpoints",
    "install", "uninstall",
]


class SchedulePolicy:
    """Decides, per yieldpoint, how many extra passes through the
    ready queue to force before the current task may proceed."""

    def __init__(self, mode: str = "random", seed: int = 0,
                 prob: float = 1.0, max_preempts: int = 64,
                 sites: Sequence[str] = (),
                 script: Optional[Iterable[int]] = None) -> None:
        if mode not in ("random", "targeted", "script"):
            raise ValueError(f"unknown schedule mode: {mode!r}")
        self.mode = mode
        self.seed = seed
        self.prob = prob
        # a global preemption budget bounds adversarial overhead: a
        # hot loop with thousands of awaits still terminates
        self.max_preempts = max_preempts
        self.sites = tuple(sites)
        self._script: List[int] = list(script or ())
        self._cursor = 0
        self._rng = random.Random(seed)
        self._spent = 0
        self.trace: List[Tuple[str, int]] = []

    def decide(self, site: str) -> int:
        if self._spent >= self.max_preempts:
            self.trace.append((site, 0))
            return 0
        if self.mode == "script":
            n = (self._script[self._cursor]
                 if self._cursor < len(self._script) else 0)
            self._cursor += 1
        elif self.mode == "targeted":
            if any(s in site for s in self.sites):
                n = 1 if self._rng.random() < self.prob else 0
            else:
                n = 0
        else:  # random
            n = 1 if self._rng.random() < self.prob else 0
        self._spent += n
        self.trace.append((site, n))
        return n


@types.coroutine
def _yield_once():
    """One bare yield: parks the driver at the back of the ready
    queue, so every other ready task runs first."""
    yield


@types.coroutine
def _forward(obj):
    """Re-yield the inner coroutine's awaitable outward (the Task
    parks on the SAME future it would have without us) and hand the
    loop's wake-up value back."""
    return (yield obj)


async def _drive_coro(coro, policy: SchedulePolicy) -> object:
    """Manually step `coro`, consulting the policy at every
    suspension point.  Semantics-preserving: the outer Task parks on
    exactly the futures the inner coroutine yields; exceptions
    (including cancellation) are thrown into the inner coroutine at
    its own suspension point, as the Task would."""
    qual = getattr(coro, "__qualname__", None) or getattr(
        coro, "__name__", "coro"
    )
    step = 0
    value: object = None
    exc: Optional[BaseException] = None
    while True:
        try:
            if exc is not None:
                e, exc = exc, None
                yielded = coro.throw(e)
            else:
                yielded = coro.send(value)
        except StopIteration as si:
            return si.value
        step += 1
        site = f"{qual}:{step}"
        try:
            for _ in range(policy.decide(site)):
                await _yield_once()
        except BaseException as e:  # cancelled during a forced yield
            value, exc = None, e
            continue
        try:
            value = await _forward(yielded)
            exc = None
        except BaseException as e:
            value, exc = None, e


def _task_factory(policy: SchedulePolicy):
    def factory(loop, coro, **kwargs):
        if isinstance(coro, types.CoroutineType):
            coro = _drive_coro(coro, policy)
        return asyncio.Task(coro, loop=loop, **kwargs)
    return factory


def install(policy: SchedulePolicy,
            loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
    """Instrument `loop` (default: running loop): every task created
    from here on steps through the policy's yieldpoints."""
    loop = loop or asyncio.get_running_loop()
    loop.set_task_factory(_task_factory(policy))


def uninstall(
    loop: Optional[asyncio.AbstractEventLoop] = None
) -> None:
    loop = loop or asyncio.get_running_loop()
    loop.set_task_factory(None)


async def drive(coro, policy: SchedulePolicy) -> object:
    """Run `coro` (and every task it spawns) under the policy.
    The workload itself runs as an instrumented child task so its
    own awaits are yieldpoints too."""
    install(policy)
    try:
        return await asyncio.get_running_loop().create_task(coro)
    finally:
        uninstall()


@contextlib.contextmanager
def failpoint_yieldpoints(policy: SchedulePolicy):
    """Within the context, every ``failpoints.evaluate_async`` call
    is also a yieldpoint (site ``seam:<name>``) — the declared IO
    seams become schedule points even when the failpoint itself is
    not armed."""
    from emqx_tpu import failpoints

    orig = failpoints.evaluate_async

    async def seamed(name: str, key=None):
        for _ in range(policy.decide(f"seam:{name}")):
            await _yield_once()
        return await orig(name, key)

    failpoints.evaluate_async = seamed
    prev_enabled = failpoints.enabled
    # the seams fast-path on the module flag; without it armed the
    # patched evaluator never runs
    failpoints.enabled = True
    try:
        yield
    finally:
        failpoints.evaluate_async = orig
        failpoints.enabled = prev_enabled
