"""Operational guards: alarms, banned clients, flapping detection,
slow-subscriber tracking.

The `emqx_alarm` / `emqx_banned` / `emqx_flapping` / `emqx_slow_subs`
slice (/root/reference/apps/emqx/src/emqx_alarm.erl, emqx_banned.erl,
emqx_flapping.erl; apps/emqx_slow_subs): alarms are an
activate/deactivate registry published to ``$SYS`` and surfaced over
REST; bans deny CONNECT by clientid/username/peerhost with expiry;
flapping detection bans clients that reconnect too fast; slow subs
keep a top-K table of delivery latency.
"""

from __future__ import annotations

import heapq
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple


@dataclass
class Alarm:
    name: str
    details: Dict = field(default_factory=dict)
    message: str = ""
    activated_at: float = 0.0
    deactivated_at: Optional[float] = None
    expires_at: Optional[float] = None  # auto-deactivate deadline

    @property
    def active(self) -> bool:
        return self.deactivated_at is None


class AlarmRegistry:
    """activate/deactivate with history (emqx_alarm.erl), publishing
    ``$SYS/brokers/<node>/alarms/...`` through the broker.

    Flap damping (per call, default off — legacy semantics hold):
    ``deactivate(name, hold=N)`` parks the deactivation for N seconds
    (processed by `tick`), and an ``activate``/``update`` inside the
    hold CANCELS it — a condition square-waving near its threshold
    costs one activate publish, one eventual deactivate, not one pair
    per oscillation.  ``update(..., min_reraise=N)`` refreshes a
    STANDING alarm's details with the re-publish throttled to one per
    N seconds.  A PUBLISHED deactivate always resets the throttle:
    state changes visible on $SYS are never suppressed — damping only
    thins refreshes of an already-raised alarm."""

    def __init__(self, broker=None, history_cap: int = 256) -> None:
        self.broker = broker
        self.history_cap = history_cap
        self._active: Dict[str, Alarm] = {}
        self._history: List[Alarm] = []
        # name -> wall ts of the last published *activate* (re-raise
        # throttling) / pending-deactivation deadlines (hysteresis)
        self._last_raise: Dict[str, float] = {}
        self._pending_deact: Dict[str, float] = {}

    def activate(
        self,
        name: str,
        details: Optional[Dict] = None,
        message: str = "",
        ttl: Optional[float] = None,
        min_reraise: float = 0.0,
        now: Optional[float] = None,
    ) -> bool:
        now = time.time() if now is None else now
        if name in self._active:
            # the condition re-asserted: a pending (held) deactivation
            # is cancelled without any $SYS churn
            self._pending_deact.pop(name, None)
            return False  # already active (duplicate activation ignored)
        alarm = Alarm(
            name=name,
            details=dict(details or {}),
            message=message or name,
            activated_at=now,
            expires_at=None if ttl is None else now + ttl,
        )
        self._active[name] = alarm
        fl = getattr(self.broker, "flight", None)
        if fl is not None:
            fl.alarm_edge(name, True)
        if min_reraise > 0.0:
            # an inactive->active transition ALWAYS publishes (any
            # prior published deactivate cleared the throttle); the
            # stamp arms `update`'s refresh damping.  Only damped
            # alarms are tracked: per-client names (flapping/<cid>,
            # conn_congestion/<cid>) never pass min_reraise, so
            # client churn cannot grow this dict.
            self._last_raise[name] = now
        self._publish("alarms/activate", alarm)
        return True

    def update(
        self,
        name: str,
        details: Optional[Dict] = None,
        message: str = "",
        min_reraise: float = 0.0,
        now: Optional[float] = None,
    ) -> bool:
        """Refresh an ACTIVE alarm's details/message in place (or
        activate it): publishes an activate message, throttled by
        ``min_reraise`` — the olp ladder's level changes ride one
        standing alarm instead of a deactivate/activate pair."""
        now = time.time() if now is None else now
        alarm = self._active.get(name)
        if alarm is None:
            return self.activate(
                name, details=details, message=message,
                min_reraise=min_reraise, now=now,
            )
        self._pending_deact.pop(name, None)
        if details is not None:
            alarm.details = dict(details)
        if message:
            alarm.message = message
        if min_reraise > 0.0:
            if (
                now - self._last_raise.get(name, float("-inf"))
                < min_reraise
            ):
                return False  # updated silently (damped)
            self._last_raise[name] = now  # damped alarms only (churn)
        self._publish("alarms/activate", alarm)
        return True

    def deactivate(
        self,
        name: str,
        hold: float = 0.0,
        now: Optional[float] = None,
    ) -> bool:
        now = time.time() if now is None else now
        if hold > 0.0:
            if name not in self._active:
                return False
            # hysteresis: park the deactivation; `tick` completes it
            # unless an activate/update cancels it first.  setdefault:
            # repeated held deactivates never push the deadline out.
            self._pending_deact.setdefault(name, now + hold)
            return False
        self._pending_deact.pop(name, None)
        alarm = self._active.pop(name, None)
        if alarm is None:
            return False
        alarm.deactivated_at = now
        self._history.append(alarm)
        del self._history[: -self.history_cap]
        # a PUBLISHED deactivate resets the re-raise damping: the
        # alarm's published state is now "inactive", so the next
        # activation must publish whatever the damping window says —
        # else a flap could leave a live alarm looking cleared for
        # the rest of the episode.  (Also keeps `_last_raise` from
        # outliving its alarm.)
        self._last_raise.pop(name, None)
        fl = getattr(self.broker, "flight", None)
        if fl is not None:
            fl.alarm_edge(name, False)
        self._publish("alarms/deactivate", alarm)
        return True

    def _publish(self, suffix: str, alarm: Alarm) -> None:
        if self.broker is None:
            return
        import json

        from .message import Message

        self.broker.metrics.inc("alarms." + suffix.rsplit("/", 1)[-1])
        node = self.broker.config.node_name
        self.broker.publish(
            Message(
                topic=f"$SYS/brokers/{node}/{suffix}",
                payload=json.dumps(
                    {"name": alarm.name, "message": alarm.message,
                     "details": alarm.details}
                ).encode(),
                sys=True,
            )
        )

    def tick(self, now: Optional[float] = None) -> None:
        """Auto-deactivate alarms past their ttl (per-client flapping
        alarms would otherwise accumulate forever) and complete held
        deactivations whose hysteresis hold elapsed un-cancelled."""
        now = now if now is not None else time.time()
        for name in [
            n
            for n, a in self._active.items()
            if a.expires_at is not None and now > a.expires_at
        ]:
            self.deactivate(name, now=now)
        for name in [
            n for n, at in self._pending_deact.items() if now >= at
        ]:
            self.deactivate(name, now=now)

    def active(self) -> List[Alarm]:
        return list(self._active.values())

    def history(self) -> List[Alarm]:
        return list(self._history)


class BannedList:
    """Deny CONNECT by clientid / username / peerhost until an expiry
    (emqx_banned.erl's mnesia table, node-local here)."""

    def __init__(self) -> None:
        # (kind, value) -> (until_ts | None, reason)
        self._entries: Dict[Tuple[str, str], Tuple[Optional[float], str]] = {}

    def ban(
        self,
        kind: str,
        value: str,
        seconds: Optional[float] = None,
        reason: str = "",
    ) -> None:
        until = None if seconds is None else time.time() + seconds
        self._entries[(kind, value)] = (until, reason)

    def unban(self, kind: str, value: str) -> bool:
        return self._entries.pop((kind, value), None) is not None

    def _check_one(self, kind: str, value: Optional[str]) -> bool:
        if value is None:
            return False
        entry = self._entries.get((kind, value))
        if entry is None:
            return False
        until, _ = entry
        if until is not None and time.time() > until:
            del self._entries[(kind, value)]
            return False
        return True

    def is_banned(
        self,
        clientid: Optional[str] = None,
        username: Optional[str] = None,
        peerhost: Optional[str] = None,
    ) -> bool:
        return (
            self._check_one("clientid", clientid)
            or self._check_one("username", username)
            or self._check_one("peerhost", peerhost)
        )

    def all(self) -> List[Dict]:
        now = time.time()
        return [
            {"as": k, "who": v, "until": until, "reason": reason}
            for (k, v), (until, reason) in self._entries.items()
            if until is None or until > now
        ]


class FlappingDetector:
    """Clients reconnecting more than ``max_count`` times inside
    ``window`` seconds get banned for ``ban_time`` (emqx_flapping.erl)."""

    def __init__(
        self,
        banned: BannedList,
        max_count: int = 15,
        window: float = 60.0,
        ban_time: float = 300.0,
        enable: bool = True,
    ) -> None:
        self.banned = banned
        self.max_count = max_count
        self.window = window
        self.ban_time = ban_time
        self.enable = enable
        # deque per client: trimming the window is popleft (O(1) per
        # expired hit) — list.pop(0) shifted the whole window on every
        # reconnect of a burst (O(window) per hit)
        self._hits: Dict[str, Deque[float]] = {}

    def on_disconnect(self, clientid: str) -> bool:
        """Record a connection cycle; returns True when it tripped the
        detector (client banned)."""
        if not self.enable:
            return False
        now = time.time()
        if len(self._hits) > 10_000:
            # amortized sweep: rotating clientids must not leak entries
            cutoff_all = now - self.window
            self._hits = {
                cid: ts
                for cid, ts in self._hits.items()
                if ts and ts[-1] >= cutoff_all
            }
        hits = self._hits.setdefault(clientid, deque())
        hits.append(now)
        cutoff = now - self.window
        while hits and hits[0] < cutoff:
            hits.popleft()
        if len(hits) >= self.max_count:
            self.banned.ban(
                "clientid",
                clientid,
                seconds=self.ban_time,
                reason="flapping",
            )
            del self._hits[clientid]
            return True
        return False


class SlowSubs:
    """Top-K delivery-latency table (emqx_slow_subs): every delivery
    reports (clientid, topic, latency); the slowest K stick — but only
    for ``expire_interval`` seconds (emqx_slow_subs' expire_interval):
    without expiry a one-off stall from hours ago shadows the board
    forever, until an operator ``clear()``."""

    def __init__(
        self,
        top_k: int = 10,
        threshold_ms: float = 500.0,
        expire_interval: float = 300.0,
    ) -> None:
        self.top_k = top_k
        self.threshold_ms = threshold_ms
        self.expire_interval = expire_interval
        # min-heap of (latency_ms, seq, clientid, topic, ts)
        self._heap: List[Tuple] = []
        self._seq = 0

    def record(self, clientid: str, topic: str, latency_ms: float,
               trace_id: str = "") -> None:
        """``trace_id``: a sampled message's lifecycle trace id, so a
        slow delivery is directly openable as a full trace (empty for
        unsampled deliveries).  Rides the END of the heap tuple —
        (latency, seq) stay the unique ordering keys."""
        if latency_ms < self.threshold_ms:
            return
        self._seq += 1
        item = (latency_ms, self._seq, clientid, topic, time.time(),
                trace_id)
        if len(self._heap) < self.top_k:
            heapq.heappush(self._heap, item)
        elif item > self._heap[0]:
            heapq.heapreplace(self._heap, item)

    def tick(self, now: Optional[float] = None) -> int:
        """Drop entries older than ``expire_interval``; returns the
        number expired.  Driven by the broker's 1 Hz housekeeping."""
        if not self._heap or self.expire_interval <= 0:
            return 0
        now = now if now is not None else time.time()
        cutoff = now - self.expire_interval
        live = [it for it in self._heap if it[4] >= cutoff]
        expired = len(self._heap) - len(live)
        if expired:
            heapq.heapify(live)
            self._heap = live
        return expired

    def top(self) -> List[Dict]:
        return [
            {
                "clientid": cid,
                "topic": topic,
                "latency_ms": round(lat, 3),
                "at": ts,
                "trace_id": trace_id,
            }
            for lat, _, cid, topic, ts, trace_id
            in sorted(self._heap, reverse=True)
        ]

    def clear(self) -> None:
        self._heap = []
