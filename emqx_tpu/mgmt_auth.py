"""Management-plane authentication: admin users with JWT login, API
keys, role-based access, all persisted to disk.

The `emqx_mgmt_auth` + dashboard-admin roles
(/root/reference/apps/emqx_management/src/emqx_mgmt_auth.erl API-key
table with hashed secrets + expiry + roles,
/root/reference/apps/emqx_dashboard/src/emqx_dashboard_admin.erl
admin users + sign_token, emqx_dashboard_rbac role check): every
/api/v5 route answers 401 without credentials; operators authenticate
either interactively (POST /api/v5/login -> Bearer JWT) or
programmatically (HTTP Basic with an API key/secret pair whose secret
is shown exactly once at creation, stored hashed).

Roles (emqx_dashboard_rbac):
  * ``administrator`` — full access.
  * ``viewer``        — read-only (GET/HEAD); mutations answer 403.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import os
import secrets
import time
from typing import Any, Dict, Optional, Tuple

from .auth_providers import make_jwt, _b64url_decode

log = logging.getLogger("emqx_tpu.mgmt_auth")

ROLE_ADMIN = "administrator"
ROLE_VIEWER = "viewer"
# the EE dashboard/API rbac's third role: may POST the message-publish
# endpoints and NOTHING else — not even reads (an ingestion credential
# that leaks cannot enumerate the deployment)
ROLE_PUBLISHER = "publisher"
_ROLES = (ROLE_ADMIN, ROLE_VIEWER, ROLE_PUBLISHER)

_PBKDF2_ITERS = 50_000


def _hash_password(password: str, salt: Optional[bytes] = None
                   ) -> Tuple[str, str]:
    salt = salt if salt is not None else os.urandom(16)
    digest = hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, _PBKDF2_ITERS
    )
    return salt.hex(), digest.hex()


def _verify_password(password: str, salt_hex: str, hash_hex: str) -> bool:
    digest = hashlib.pbkdf2_hmac(
        "sha256", password.encode(), bytes.fromhex(salt_hex), _PBKDF2_ITERS
    )
    return hmac.compare_digest(digest.hex(), hash_hex)


class Identity:
    """Who an authenticated management request is acting as."""

    __slots__ = ("actor", "role", "via")

    def __init__(self, actor: str, role: str, via: str) -> None:
        self.actor = actor  # username or api key id
        self.role = role
        self.via = via  # "token" | "api_key"

    @property
    def can_write(self) -> bool:
        return self.role == ROLE_ADMIN

    @property
    def publish_only(self) -> bool:
        return self.role == ROLE_PUBLISHER


class MgmtAuth:
    """Persisted admin-user + API-key stores and token mint/verify.

    State lives under ``data_dir``: ``admins.json``, ``api_keys.json``
    and ``jwt.secret`` (random per deployment, persisted so issued
    tokens survive a broker restart, like the dashboard's stored JWKS).
    """

    def __init__(
        self,
        data_dir: str,
        default_username: str = "admin",
        default_password: Optional[str] = "public",
        token_ttl: float = 3600.0,
    ) -> None:
        self.data_dir = data_dir
        self.token_ttl = token_ttl
        os.makedirs(data_dir, exist_ok=True)
        self._admins_path = os.path.join(data_dir, "admins.json")
        self._keys_path = os.path.join(data_dir, "api_keys.json")
        self._secret_path = os.path.join(data_dir, "jwt.secret")
        self.admins: Dict[str, Dict[str, Any]] = self._load(self._admins_path)
        self.api_keys: Dict[str, Dict[str, Any]] = self._load(self._keys_path)
        # api_key -> sha256(secret) after one successful slow verify
        self._fast: Dict[str, str] = {}
        self.secret = self._load_secret()
        if not self.admins and default_password is not None:
            # first boot: seed the default admin (the reference ships
            # admin/public and forces a change at first dashboard login;
            # here operators change it via POST /api/v5/users/.../change_pwd)
            self.add_admin(default_username, default_password, ROLE_ADMIN)
            log.warning(
                "mgmt auth: bootstrapped default admin %r — change its "
                "password", default_username,
            )

    # ------------------------------------------------------ persistence

    @staticmethod
    def _load(path: str) -> Dict[str, Any]:
        """Absent file = first boot; a PRESENT but unreadable/corrupt
        store is a hard error — treating it as empty would silently
        re-bootstrap the default admin/public credentials over the
        operator's user table."""
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}
        except (OSError, json.JSONDecodeError) as exc:
            raise RuntimeError(
                f"management auth store {path} is unreadable or corrupt "
                f"({exc}); refusing to start with default credentials — "
                "repair or remove the file explicitly"
            ) from exc

    @staticmethod
    def _save(path: str, data: Dict[str, Any]) -> None:
        # owner-only like the jwt secret: these stores hold credential
        # hashes/salts, and the default umask would leave them
        # world-readable
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1)
        os.replace(tmp, path)

    def _load_secret(self) -> bytes:
        """Same policy as _load: absent = generate; present-but-broken
        = hard error (a silently regenerated secret would invalidate
        every issued token while masking the underlying disk fault)."""
        try:
            with open(self._secret_path, "rb") as f:
                secret = f.read()
        except FileNotFoundError:
            secret = os.urandom(32)
            tmp = self._secret_path + ".tmp"
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(secret)
            os.replace(tmp, self._secret_path)
            return secret
        except OSError as exc:
            raise RuntimeError(
                f"jwt secret {self._secret_path} unreadable ({exc})"
            ) from exc
        if len(secret) < 32:
            raise RuntimeError(
                f"jwt secret {self._secret_path} is truncated "
                f"({len(secret)} bytes); remove it explicitly to rotate"
            )
        return secret

    # ----------------------------------------------------- admin users

    def add_admin(self, username: str, password: str,
                  role: str = ROLE_ADMIN) -> None:
        if role not in _ROLES:
            raise ValueError(f"unknown role {role!r}")
        if not username or not password:
            raise ValueError("username and password required")
        salt, pw = _hash_password(password)
        self.admins[username] = {"salt": salt, "hash": pw, "role": role}
        self._save(self._admins_path, self.admins)

    def delete_admin(self, username: str) -> bool:
        user = self.admins.get(username)
        if user is None:
            return False
        if user["role"] == ROLE_ADMIN and sum(
            1 for u in self.admins.values() if u["role"] == ROLE_ADMIN
        ) == 1:
            # deleting the last administrator would lock the plane and,
            # worse, the next restart would re-seed default credentials
            raise ValueError("cannot delete the last administrator")
        del self.admins[username]
        self._save(self._admins_path, self.admins)
        return True

    def change_password(self, username: str, old: str, new: str) -> bool:
        user = self.admins.get(username)
        if user is None or not _verify_password(
            old, user["salt"], user["hash"]
        ):
            return False
        if not new:
            raise ValueError("empty password")
        user["salt"], user["hash"] = _hash_password(new)
        # token epoch: every Bearer token minted BEFORE this moment is
        # dead — rotating a compromised password must end the
        # attacker's session too (the reference destroys tokens in
        # emqx_dashboard_admin on password change)
        user["pwd_changed_at"] = time.time()
        self._save(self._admins_path, self.admins)
        return True

    def login(self, username: str, password: str) -> Optional[str]:
        """Verify credentials; mint a Bearer token (sign_token)."""
        user = self.admins.get(username)
        if user is None or not _verify_password(
            password, user["salt"], user["hash"]
        ):
            return None
        now = time.time()
        return make_jwt(self.secret, {
            "sub": username,
            "role": user["role"],
            "iat": now,
            "exp": now + self.token_ttl,
        })

    def verify_token(self, token: str) -> Optional[Identity]:
        try:
            head_b64, body_b64, sig_b64 = token.split(".")
            header = json.loads(_b64url_decode(head_b64))
            if header.get("alg") != "HS256":
                return None
            expect = hmac.new(
                self.secret, f"{head_b64}.{body_b64}".encode(),
                hashlib.sha256,
            ).digest()
            if not hmac.compare_digest(expect, _b64url_decode(sig_b64)):
                return None
            claims = json.loads(_b64url_decode(body_b64))
        except (ValueError, json.JSONDecodeError):
            return None
        if time.time() > float(claims.get("exp", 0)):
            return None
        username = claims.get("sub", "")
        user = self.admins.get(username)
        if user is None:
            return None  # deleted since the token was minted
        if float(claims.get("iat", 0)) < float(
            user.get("pwd_changed_at", 0)
        ):
            return None  # minted before the last password rotation
        # role comes from the LIVE record, not the token: demoting a
        # user takes effect immediately
        return Identity(username, user["role"], "token")

    # -------------------------------------------------------- API keys

    def create_api_key(
        self,
        name: str,
        role: str = ROLE_ADMIN,
        expires_in: Optional[float] = None,
        enabled: bool = True,
    ) -> Tuple[str, str]:
        """Mint a key/secret pair; the plaintext secret is returned
        exactly once (emqx_mgmt_auth:create stores the hash)."""
        if role not in _ROLES:
            raise ValueError(f"unknown role {role!r}")
        if not name:
            raise ValueError("name required")
        api_key = "key-" + secrets.token_hex(8)
        api_secret = secrets.token_urlsafe(24)
        salt, sh = _hash_password(api_secret)
        self.api_keys[api_key] = {
            "name": name,
            "role": role,
            "salt": salt,
            "hash": sh,
            "enabled": enabled,
            "created_at": time.time(),
            "expired_at": (time.time() + expires_in)
            if expires_in is not None else None,
        }
        self._save(self._keys_path, self.api_keys)
        return api_key, api_secret

    def delete_api_key(self, api_key: str) -> bool:
        if self.api_keys.pop(api_key, None) is None:
            return False
        self._fast.pop(api_key, None)
        self._save(self._keys_path, self.api_keys)
        return True

    def set_api_key_enabled(self, api_key: str, enabled: bool) -> bool:
        entry = self.api_keys.get(api_key)
        if entry is None:
            return False
        entry["enabled"] = enabled
        if not enabled:
            self._fast.pop(api_key, None)
        self._save(self._keys_path, self.api_keys)
        return True

    def verify_api_key(self, api_key: str,
                       api_secret: str) -> Optional[Identity]:
        entry = self.api_keys.get(api_key)
        if entry is None or not entry.get("enabled", True):
            return None
        exp = entry.get("expired_at")
        if exp is not None and time.time() > float(exp):
            return None
        # the slow (on-disk) hash runs once per key; later requests on
        # the broker's event loop compare a cached in-memory digest —
        # 50k PBKDF2 rounds per Basic-authenticated request would stall
        # MQTT traffic sharing the loop
        fast = hashlib.sha256(api_secret.encode()).hexdigest()
        cached = self._fast.get(api_key)
        if cached is not None:
            if not hmac.compare_digest(cached, fast):
                return None
        else:
            if not _verify_password(
                api_secret, entry["salt"], entry["hash"]
            ):
                return None
            self._fast[api_key] = fast
        return Identity(api_key, entry["role"], "api_key")

    # ------------------------------------------------------ HTTP glue

    def authenticate_header(self, header: Optional[str]
                            ) -> Optional[Identity]:
        """Resolve an ``Authorization`` header to an identity:
        ``Bearer <jwt>`` (dashboard token) or ``Basic key:secret``
        (API key, as the reference's API consumers send)."""
        if not header:
            return None
        scheme, _, rest = header.partition(" ")
        scheme = scheme.lower()
        if scheme == "bearer" and rest:
            return self.verify_token(rest.strip())
        if scheme == "basic" and rest:
            try:
                raw = base64.b64decode(rest.strip()).decode()
                key, _, secret = raw.partition(":")
            except (ValueError, UnicodeDecodeError):
                return None
            return self.verify_api_key(key, secret)
        return None

    def info(self) -> list:
        return [
            {
                "api_key": k,
                "name": e["name"],
                "role": e["role"],
                "enabled": e.get("enabled", True),
                "created_at": e.get("created_at"),
                "expired_at": e.get("expired_at"),
            }
            for k, e in self.api_keys.items()
        ]


class AuditLog:
    """Persisted audit trail of mutating API/CLI calls (the reference
    persists these in mnesia, emqx_audit.erl; here an append-only JSONL
    file reloaded on boot — an audit trail must survive a restart)."""

    def __init__(self, data_dir: str, cap: int = 1000) -> None:
        self.cap = cap
        os.makedirs(data_dir, exist_ok=True)
        self.path = os.path.join(data_dir, "audit.jsonl")
        self.entries: list = []
        self._file_lines = 0
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._file_lines += 1
                        try:
                            self.entries.append(json.loads(line))
                        except json.JSONDecodeError:
                            continue
            self.entries = self.entries[-cap:]
        except OSError:
            pass

    def append(self, entry: Dict[str, Any]) -> None:
        self.entries.append(entry)
        del self.entries[: -self.cap]
        try:
            if self._file_lines >= self.cap * 10:
                # compact instead of growing without bound: rewrite the
                # retained window (the reference's mnesia table is
                # similarly capped by emqx_audit's max_size)
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    for e in self.entries:
                        f.write(json.dumps(e, separators=(",", ":"))
                                + "\n")
                os.replace(tmp, self.path)
                self._file_lines = len(self.entries)
            else:
                with open(self.path, "a") as f:
                    f.write(json.dumps(entry, separators=(",", ":"))
                            + "\n")
                self._file_lines += 1
        except OSError:
            log.exception("audit append failed")
