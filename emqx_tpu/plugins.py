"""Plugin loader: operator-supplied Python extensions.

The `emqx_plugins` role (/root/reference/apps/emqx_plugins/src:
installable packages registering hooks at boot, with enable/disable
order): a plugin is either

  * a single ``<name>.py`` file in the plugin directory (or an
    importable module path), or
  * an installable PACKAGE ``<name>-<vsn>.tar.gz`` (the reference's
    release-package shape): a tarball holding ``release.json``
    ({"name", "rel_vsn", "description", ...}) plus the plugin's
    Python sources, installed into ``<dir>/<name>-<vsn>/`` via
    `install_package` and loaded by its release name.

Either form exposes ``def setup(broker) -> None | object``; ``setup``
registers hooks/rules/resources against the broker; the optional
return value is retained and, if it has ``teardown(broker)``, called
at unload.  Plugins load in configured order at server start.
"""

from __future__ import annotations

import importlib
import importlib.util
import json
import logging
import os
import tarfile
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.plugins")


class PluginManager:
    def __init__(self, broker, directory: str = "plugins") -> None:
        self.broker = broker
        self.directory = directory
        self._loaded: Dict[str, object] = {}

    def install_package(self, tgz_path: str) -> str:
        """Install a ``<name>-<vsn>.tar.gz`` release package into the
        plugin directory (emqx_plugins:ensure_installed): validates
        release.json, extracts under ``<dir>/<name>-<vsn>/``, and
        returns the release name for `load`.  Member paths are
        sanitized — a package must not write outside its own tree."""
        with tarfile.open(tgz_path, "r:gz") as tf:
            names = tf.getnames()
            rel_member = next(
                (n for n in names
                 if n.rstrip("/").endswith("release.json")), None
            )
            if rel_member is None:
                raise ValueError("package has no release.json")
            meta = json.load(tf.extractfile(rel_member))
            name = meta.get("name")
            vsn = meta.get("rel_vsn")
            if not name or not vsn:
                raise ValueError("release.json missing name/rel_vsn")
            rel = f"{name}-{vsn}"
            dest = os.path.join(self.directory, rel)
            os.makedirs(dest, exist_ok=True)
            for member in tf.getmembers():
                target = os.path.normpath(member.name)
                if target.startswith(("..", "/")):
                    raise ValueError(
                        f"unsafe member path {member.name!r}"
                    )
                if member.isfile():
                    # flatten one leading '<rel>/' dir if present
                    parts = target.split("/")
                    if parts[0] == rel and len(parts) > 1:
                        target = "/".join(parts[1:])
                    out = os.path.join(dest, target)
                    os.makedirs(os.path.dirname(out), exist_ok=True)
                    with open(out, "wb") as f:
                        f.write(tf.extractfile(member).read())
        log.info("plugin package %s installed", rel)
        return rel

    def _package_module(self, name: str):
        """A ``<name>-<vsn>`` directory with release.json is a
        package: its entry module is ``<name>.py`` inside (or the
        release.json "entry")."""
        pdir = os.path.join(self.directory, name)
        rel_path = os.path.join(pdir, "release.json")
        if not os.path.isdir(pdir) or not os.path.exists(rel_path):
            return None
        with open(rel_path) as f:
            meta = json.load(f)
        entry = meta.get("entry", f"{meta.get('name', name)}.py")
        spec = importlib.util.spec_from_file_location(
            f"emqx_tpu_plugin_{name}", os.path.join(pdir, entry)
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module

    def load(self, name: str) -> bool:
        """Load one plugin by name: an installed package directory
        first, then `<dir>/<name>.py`, else an importable module
        path."""
        if name in self._loaded:
            return False
        path = os.path.join(self.directory, f"{name}.py")
        try:
            module = self._package_module(name)
            if module is not None:
                pass
            elif os.path.exists(path):
                spec = importlib.util.spec_from_file_location(
                    f"emqx_tpu_plugin_{name}", path
                )
                module = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(module)
            else:
                module = importlib.import_module(name)
            handle = module.setup(self.broker)
        except Exception:
            log.exception("plugin %s failed to load", name)
            self.broker.metrics.inc("plugins.load_failed")
            return False
        self._loaded[name] = handle
        self.broker.metrics.inc("plugins.loaded")
        log.info("plugin %s loaded", name)
        return True

    def unload(self, name: str) -> bool:
        handle = self._loaded.pop(name, None)
        if handle is None:
            return False
        teardown = getattr(handle, "teardown", None)
        if teardown is not None:
            try:
                teardown(self.broker)
            except Exception:
                log.exception("plugin %s teardown failed", name)
        return True

    def unload_all(self) -> None:
        for name in list(self._loaded):
            self.unload(name)

    def info(self) -> List[Dict]:
        return [{"name": n, "status": "running"} for n in self._loaded]
