"""Plugin loader: operator-supplied Python extensions.

The `emqx_plugins` role (/root/reference/apps/emqx_plugins/src:
installable packages registering hooks at boot, with enable/disable
order): here a plugin is a Python module (a single ``<name>.py`` file
in the plugin directory, or an importable module path) exposing

    def setup(broker) -> None | object

``setup`` registers hooks/rules/resources against the broker; the
optional return value is retained and, if it has ``teardown(broker)``,
called at unload.  Plugins load in configured order at server start.
"""

from __future__ import annotations

import importlib
import importlib.util
import logging
import os
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.plugins")


class PluginManager:
    def __init__(self, broker, directory: str = "plugins") -> None:
        self.broker = broker
        self.directory = directory
        self._loaded: Dict[str, object] = {}

    def load(self, name: str) -> bool:
        """Load one plugin by name: `<dir>/<name>.py` first, else an
        importable module path."""
        if name in self._loaded:
            return False
        path = os.path.join(self.directory, f"{name}.py")
        try:
            if os.path.exists(path):
                spec = importlib.util.spec_from_file_location(
                    f"emqx_tpu_plugin_{name}", path
                )
                module = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(module)
            else:
                module = importlib.import_module(name)
            handle = module.setup(self.broker)
        except Exception:
            log.exception("plugin %s failed to load", name)
            self.broker.metrics.inc("plugins.load_failed")
            return False
        self._loaded[name] = handle
        self.broker.metrics.inc("plugins.loaded")
        log.info("plugin %s loaded", name)
        return True

    def unload(self, name: str) -> bool:
        handle = self._loaded.pop(name, None)
        if handle is None:
            return False
        teardown = getattr(handle, "teardown", None)
        if teardown is not None:
            try:
                teardown(self.broker)
            except Exception:
                log.exception("plugin %s teardown failed", name)
        return True

    def unload_all(self) -> None:
        for name in list(self._loaded):
            self.unload(name)

    def info(self) -> List[Dict]:
        return [{"name": n, "status": "running"} for n in self._loaded]
