"""Data backup/restore: one archive for a node's operational state.

The `emqx_mgmt_data_backup` role (/root/reference/apps/
emqx_management/src/emqx_mgmt_data_backup.erl, 996 LoC: tar of config
+ mnesia tables with per-table import, version checks, and a result
report): `export_archive` writes a ``.tar.gz`` holding the config
tree, retained messages, the banned table, SQL rules, and the
management-auth stores; `import_archive` restores them into a RUNNING
broker, applying config through the validating update path and
reporting what was restored and what was skipped.

Structural config (listeners, node/cluster identity, durable storage
layout) is deliberately NOT hot-applied — the reference's import
equally refuses settings that require a reboot — it is still in the
archive for a fresh node booting from it.
"""

from __future__ import annotations

import dataclasses
import io
import json
import logging
import os
import tarfile
import time
from typing import Any, Dict, List, Optional, Tuple

from .cluster.node import msg_from_wire, msg_to_wire

log = logging.getLogger("emqx_tpu.backup")

FORMAT_VERSION = 1

# config roots that cannot hot-apply into a running broker
_STRUCTURAL = (
    "listeners", "node_name", "cluster_name", "durable", "api",
    "plugin_dir", "plugins", "gateways", "exhooks", "cluster_links",
)


def _flatten(prefix: str, obj: Any, out: Dict[str, Any]) -> None:
    if dataclasses.is_dataclass(obj):
        obj = dataclasses.asdict(obj)
    if isinstance(obj, dict) and obj and all(
        isinstance(k, str) for k in obj
    ):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else k, v, out)
    else:
        out[prefix] = obj


def gather_state(server) -> Tuple[Dict[str, bytes], Dict]:
    """Serialize the broker state into archive members.  MUST run on
    the event loop (it iterates loop-owned structures — the retainer
    trie, rule/banned tables; a worker thread would race concurrent
    publishes); it is pure dict walks, fast enough to stay inline.
    Returns (members, manifest)."""
    from .config import ConfigHandler

    broker = server.broker
    members: Dict[str, bytes] = {}
    members["cluster.json"] = json.dumps(
        ConfigHandler(broker.config).to_dict(), indent=1, default=str
    ).encode()
    retained = [
        msg_to_wire(m) for m in broker.retainer.match("#")
    ] + [
        # '#' misses $-topics by MQTT rules; export those explicitly
        msg_to_wire(m)
        for t in broker.retainer.topics() if t.startswith("$")
        for m in broker.retainer.match(t)
    ]
    members["retained.jsonl"] = "\n".join(
        json.dumps(w, separators=(",", ":")) for w in retained
    ).encode()
    members["banned.json"] = json.dumps(broker.banned.all()).encode()
    members["rules.json"] = json.dumps([
        {
            "id": r.rule_id,
            "sql": r.sql,
            "enabled": r.enabled,
            "description": r.description,
        }
        for r in broker.rules.rules.values()
    ]).encode()
    api = getattr(server, "api", None)
    if api is not None:
        members["mgmt/admins.json"] = json.dumps(api.auth.admins).encode()
        members["mgmt/api_keys.json"] = json.dumps(
            api.auth.api_keys
        ).encode()
    from .schema_registry import global_registry

    members["schemas.json"] = json.dumps(
        global_registry().dump()
    ).encode()

    manifest = {
        "version": FORMAT_VERSION,
        "exported_at": time.time(),
        "node": broker.config.node_name,
        "counts": {
            "retained": len(retained),
            "banned": len(broker.banned.all()),
            "rules": len(broker.rules.rules),
        },
    }
    members["META.json"] = json.dumps(manifest, indent=1).encode()
    return members, manifest


def write_archive(
    members: Dict[str, bytes], directory: str
) -> str:
    """Tar+gzip the gathered members to disk (pure bytes work — safe
    in a worker thread)."""
    os.makedirs(directory, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    path = os.path.join(directory, f"emqx-export-{stamp}.tar.gz")
    with tarfile.open(path, "w:gz") as tar:
        for name, data in members.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))
    return path


def export_archive(
    server, directory: Optional[str] = None
) -> Tuple[str, Dict]:
    """Gather + write in one call (CLI/tests; the REST handler splits
    the phases so only the bytes work leaves the event loop)."""
    directory = directory or os.path.join(
        server.broker.config.api.data_dir, "backups"
    )
    members, manifest = gather_state(server)
    path = write_archive(members, directory)
    log.info("exported %s (%s)", path, manifest["counts"])
    return path, manifest


def parse_archive(data: bytes) -> Dict[str, bytes]:
    """Untar an uploaded archive into its members (bytes work — safe
    in a worker thread); validates the format version."""
    try:
        tar = tarfile.open(fileobj=io.BytesIO(data), mode="r:gz")
    except tarfile.TarError as exc:
        raise ValueError(f"not a backup archive: {exc}") from exc
    members: Dict[str, bytes] = {}
    for info in tar.getmembers():
        f = tar.extractfile(info)
        if f is not None:
            members[info.name] = f.read()
    meta_raw = members.get("META.json")
    if meta_raw is None:
        raise ValueError("archive has no META.json")
    meta = json.loads(meta_raw)
    if int(meta.get("version", 0)) > FORMAT_VERSION:
        raise ValueError(
            f"archive format v{meta.get('version')} is newer than "
            f"this broker understands (v{FORMAT_VERSION})"
        )
    return members


def import_archive(server, data: bytes) -> Dict:
    """Parse + apply in one call (CLI/tests; the REST handler parses
    off-loop and applies via `apply_state_async`)."""
    return apply_state(server, parse_archive(data))


def apply_state(server, members: Dict[str, bytes],
                report: Optional[Dict] = None) -> Dict:
    """Restore parsed members into a running broker; returns the
    report {restored: {...}, errors: [...], skipped: [...]} (the
    reference's import result map).  Runs on the event loop (it
    mutates loop-owned structures)."""
    broker = server.broker
    if report is None:
        report = {"restored": {}, "errors": [], "skipped": []}

    def read(name: str) -> Optional[bytes]:
        return members.get(name)

    # --- config: flatten and apply leaf-by-leaf through the
    # validating update path; structural roots are reported skipped
    conf_raw = read("cluster.json")
    if conf_raw is not None:
        try:
            conf_obj = json.loads(conf_raw)
        except (ValueError, UnicodeDecodeError) as exc:
            report["errors"].append(f"cluster.json: {exc}")
            conf_obj = None
    else:
        conf_obj = None
    if conf_obj is not None:
        flat: Dict[str, Any] = {}
        _flatten("", conf_obj, flat)
        current: Dict[str, Any] = {}
        _flatten("", broker.config, current)
        applied = 0
        for path, value in flat.items():
            root = path.split(".", 1)[0]
            if root in _STRUCTURAL:
                if root not in report["skipped"]:
                    report["skipped"].append(root)
                continue
            if current.get(path, object()) == value:
                continue  # unchanged
            try:
                broker.apply_config(path, value)
                applied += 1
            except Exception as exc:
                report["errors"].append(f"config {path}: {exc}")
        report["skipped"].sort()
        report["restored"]["config_keys"] = applied

    # --- retained messages
    ret_raw = read("retained.jsonl")
    if ret_raw is not None:
        n = 0
        for line in ret_raw.decode(errors="replace").splitlines():
            n += _store_retained_line(broker, line, report)
        report["restored"]["retained"] = n

    # --- banned table
    ban_raw = read("banned.json")
    if ban_raw is not None:
        try:
            ban_entries = json.loads(ban_raw)
        except (ValueError, UnicodeDecodeError) as exc:
            report["errors"].append(f"banned.json: {exc}")
            ban_entries = []
        n = 0
        now = time.time()
        for entry in ban_entries:
            try:
                until = entry.get("until")
                seconds = None
                if until is not None:
                    seconds = max(float(until) - now, 0.0)
                    if seconds == 0.0:
                        continue  # already expired
                broker.banned.ban(
                    entry["as"], entry["who"],
                    seconds=seconds,
                    reason=entry.get("reason", ""),
                )
                n += 1
            except Exception as exc:
                report["errors"].append(f"banned: {exc}")
        report["restored"]["banned"] = n

    # --- SQL rules (same id replaces)
    rules_raw = read("rules.json")
    if rules_raw is not None:
        try:
            rule_entries = json.loads(rules_raw)
        except (ValueError, UnicodeDecodeError) as exc:
            report["errors"].append(f"rules.json: {exc}")
            rule_entries = []
        n = 0
        for entry in rule_entries:
            try:
                broker.rules.remove_rule(entry["id"])
                broker.rules.add_rule(
                    entry["id"], entry["sql"],
                    enabled=entry.get("enabled", True),
                    description=entry.get("description", ""),
                )
                n += 1
            except Exception as exc:
                report["errors"].append(f"rule {entry.get('id')}: {exc}")
        report["restored"]["rules"] = n

    # --- management auth stores (merged: imported users/keys are
    # added/overwritten, existing extras stay — the reference merges
    # mnesia records the same way)
    api = getattr(server, "api", None)
    if api is not None:
        admins_raw = read("mgmt/admins.json")
        if admins_raw is not None:
            try:
                imported = json.loads(admins_raw)
            except (ValueError, UnicodeDecodeError) as exc:
                report["errors"].append(f"admins.json: {exc}")
                imported = {}
            api.auth.admins.update(imported)
            api.auth._save(api.auth._admins_path, api.auth.admins)
            report["restored"]["admins"] = len(imported)
        keys_raw = read("mgmt/api_keys.json")
        if keys_raw is not None:
            try:
                imported = json.loads(keys_raw)
            except (ValueError, UnicodeDecodeError) as exc:
                report["errors"].append(f"api_keys.json: {exc}")
                imported = {}
            api.auth.api_keys.update(imported)
            api.auth._save(api.auth._keys_path, api.auth.api_keys)
            report["restored"]["api_keys"] = len(imported)

    # --- schema registry
    schemas_raw = read("schemas.json")
    if schemas_raw is not None:
        from .schema_registry import global_registry

        try:
            entries = json.loads(schemas_raw)
        except (ValueError, UnicodeDecodeError) as exc:
            report["errors"].append(f"schemas.json: {exc}")
            entries = {}
        n = 0
        for name, entry in entries.items():
            try:
                global_registry().add(
                    name, entry["type"], entry["source"]
                )
                n += 1
            except Exception as exc:
                report["errors"].append(f"schema {name}: {exc}")
        report["restored"]["schemas"] = n

    log.info("import done: %s", report)
    return report


def _store_retained_line(broker, line: str, report: Dict) -> int:
    line = line.strip()
    if not line:
        return 0
    try:
        msg = msg_from_wire(json.loads(line))
        msg.retain = True
        broker.retainer.store(msg)
        return 1
    except Exception as exc:
        report["errors"].append(f"retained: {exc}")
        return 0


async def apply_state_async(server, members: Dict[str, bytes]) -> Dict:
    """apply_state for the REST path: the (possibly large) retained
    table applies in chunks with loop yields so connected clients'
    keepalives keep flowing during a restore."""
    import asyncio

    report: Dict[str, Any] = {"restored": {}, "errors": [], "skipped": []}
    small = {
        k: v for k, v in members.items() if k != "retained.jsonl"
    }
    apply_state(server, small, report)
    ret_raw = members.get("retained.jsonl")
    if ret_raw is not None:
        broker = server.broker
        n = 0
        for i, line in enumerate(ret_raw.decode().splitlines()):
            n += _store_retained_line(broker, line, report)
            if i % 500 == 499:
                await asyncio.sleep(0)
        report["restored"]["retained"] = n
    return report
