"""MongoDB authentication/authorization backend — wire protocol.

The reference's emqx_auth_mongodb
(/root/reference/apps/emqx_auth_mongodb/src/) runs `find` commands
against user/ACL collections through the mongodb driver; this module
speaks the modern wire protocol directly (OP_MSG, opcode 2013, with a
minimal BSON codec) so no driver dependency exists, and plugs the
providers into the same async chain + prefetched-ACL pattern as the
SQL/Redis backends (auth_db.py).

BSON scope: the types auth documents actually use — string, double,
int32/64, bool, null, embedded document, array.  `MongoConnector`
pipelines one command at a time per connection (requestID matched).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Any, Dict, List, Optional, Tuple

from .access import ALLOW, DENY, IGNORE, Authenticator, ClientInfo
from .auth_db import check_algorithm_supported, verify_password

log = logging.getLogger("emqx_tpu.auth_mongo")

OP_MSG = 2013


# ---------------------------------------------------------------- BSON

def bson_encode(doc: Dict[str, Any]) -> bytes:
    body = bytearray()
    for key, val in doc.items():
        kb = key.encode() + b"\x00"
        if isinstance(val, bool):  # before int: bool is an int subtype
            body += b"\x08" + kb + (b"\x01" if val else b"\x00")
        elif isinstance(val, float):
            body += b"\x01" + kb + struct.pack("<d", val)
        elif isinstance(val, int):
            if -(2 ** 31) <= val < 2 ** 31:
                body += b"\x10" + kb + struct.pack("<i", val)
            else:
                body += b"\x12" + kb + struct.pack("<q", val)
        elif isinstance(val, str):
            vb = val.encode() + b"\x00"
            body += b"\x02" + kb + struct.pack("<i", len(vb)) + vb
        elif val is None:
            body += b"\x0a" + kb
        elif isinstance(val, dict):
            body += b"\x03" + kb + bson_encode(val)
        elif isinstance(val, (list, tuple)):
            body += b"\x04" + kb + bson_encode(
                {str(i): v for i, v in enumerate(val)}
            )
        else:
            raise TypeError(f"bson: unsupported {type(val)!r}")
    return struct.pack("<i", len(body) + 5) + bytes(body) + b"\x00"


def bson_decode(data: bytes, offset: int = 0) -> Tuple[Dict[str, Any], int]:
    (total,) = struct.unpack_from("<i", data, offset)
    end = offset + total - 1  # trailing NUL
    off = offset + 4
    out: Dict[str, Any] = {}
    while off < end:
        etype = data[off]
        off += 1
        nul = data.index(b"\x00", off)
        key = data[off:nul].decode()
        off = nul + 1
        if etype == 0x01:
            (out[key],) = struct.unpack_from("<d", data, off)
            off += 8
        elif etype == 0x02:
            (ln,) = struct.unpack_from("<i", data, off)
            out[key] = data[off + 4:off + 4 + ln - 1].decode()
            off += 4 + ln
        elif etype in (0x03, 0x04):
            sub, off = bson_decode(data, off)
            out[key] = (
                [sub[str(i)] for i in range(len(sub))]
                if etype == 0x04 else sub
            )
        elif etype == 0x08:
            out[key] = data[off] == 1
            off += 1
        elif etype == 0x0A:
            out[key] = None
        elif etype == 0x10:
            (out[key],) = struct.unpack_from("<i", data, off)
            off += 4
        elif etype == 0x12:
            (out[key],) = struct.unpack_from("<q", data, off)
            off += 8
        else:
            raise ValueError(f"bson: unsupported type 0x{etype:02x}")
    return out, end + 1


# ------------------------------------------------------------ connector

class MongoConnector:
    """One OP_MSG connection; `command` runs one database command and
    returns the reply document.

    Commands PIPELINE on the single connection: OP_MSG replies carry
    ``responseTo``, so each caller registers a future under its
    request id, writes its frame, and a shared reader pump
    demultiplexes replies back — concurrent CONNECT-time auth
    lookups no longer serialize on a lock held across the full
    round-trip."""

    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 database: str = "mqtt") -> None:
        self.host = host
        self.port = port
        self.database = database
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._req = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._reader: Optional[asyncio.Task] = None
        self._connecting: Optional[asyncio.Task] = None

    async def _connect(self) -> None:
        self._r, self._w = await asyncio.open_connection(
            self.host, self.port
        )
        # fresh pending map per connection: a stale pump's teardown
        # must never fail futures registered against its successor
        self._pending = {}
        self._reader = asyncio.get_running_loop().create_task(
            self._read_loop(self._r, self._pending)
        )

    async def _ensure(self) -> None:
        """Connect once, even under concurrent callers: the first
        starts the dial, the rest await the same task."""
        if self._w is not None and not self._w.is_closing():
            return
        if self._connecting is None or self._connecting.done():
            self._connecting = asyncio.get_running_loop().create_task(
                self._connect()
            )
        await asyncio.shield(self._connecting)

    async def _read_loop(
        self, r: asyncio.StreamReader,
        pending: Dict[int, "asyncio.Future"],
    ) -> None:
        """Reader pump: demultiplex replies by ``responseTo``."""
        try:
            while True:
                hdr = await r.readexactly(16)
                length, _rid, resp_to, opcode = struct.unpack(
                    "<iiii", hdr
                )
                payload = await r.readexactly(length - 16)
                fut = pending.pop(resp_to, None)
                if fut is None or fut.done():
                    continue
                if opcode != OP_MSG:
                    fut.set_exception(
                        ConnectionError(f"unexpected opcode {opcode}")
                    )
                    continue
                try:
                    # flagBits(4) + section kind(1) + document
                    reply, _ = bson_decode(payload, 5)
                except Exception as exc:
                    fut.set_exception(exc)
                else:
                    fut.set_result(reply)
        except asyncio.CancelledError:
            raise
        except Exception:
            pass  # connection loss surfaces via the pending futures
        finally:
            exc = ConnectionError(
                f"mongo connection {self.host}:{self.port} lost"
            )
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(exc)
            pending.clear()
            # tear the transport down with the pump: a half-closed
            # socket must read as disconnected, or every later
            # command() would register in an unpumped map and stall
            # CONNECT-time auth to its timeout instead of re-dialing
            if self._r is r and self._w is not None:
                w, self._w, self._r = self._w, None, None
                w.close()

    async def command(self, doc: Dict[str, Any],
                      timeout: float = 5.0) -> Dict[str, Any]:
        await self._ensure()
        rid = next(self._req)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        doc = dict(doc)
        doc.setdefault("$db", self.database)
        body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
        msg = struct.pack(
            "<iiii", 16 + len(body), rid, 0, OP_MSG
        ) + body
        try:
            self._w.write(msg)
            await self._w.drain()
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(rid, None)

    async def find_one(self, collection: str,
                       flt: Dict[str, Any]) -> Optional[Dict]:
        reply = await self.command({
            "find": collection, "filter": flt, "limit": 1,
        })
        batch = reply.get("cursor", {}).get("firstBatch", [])
        return batch[0] if batch else None

    async def find(self, collection: str,
                   flt: Dict[str, Any]) -> List[Dict]:
        reply = await self.command({
            "find": collection, "filter": flt,
        })
        return list(reply.get("cursor", {}).get("firstBatch", []))

    async def close(self) -> None:
        if self._reader is not None:
            self._reader.cancel()
            self._reader = None
        self._connecting = None
        if self._w is not None:
            self._w.close()
            self._w = self._r = None


# ------------------------------------------------------------ providers

class MongoAuthenticator(Authenticator):
    """find-one against the user collection, password verified with
    the shared hashing suite (emqx_authn_mongodb)."""

    is_async = True

    def __init__(
        self,
        connector: MongoConnector,
        collection: str = "mqtt_user",
        filter_field: str = "username",
        algorithm: str = "sha256",
        salt_position: str = "prefix",
        iterations: int = 50_000,
    ) -> None:
        check_algorithm_supported(algorithm)
        self.connector = connector
        self.collection = collection
        self.filter_field = filter_field
        self.algorithm = algorithm
        self.salt_position = salt_position
        self.iterations = iterations

    def authenticate(self, client: ClientInfo):
        return IGNORE, {}  # async-only provider

    async def authenticate_async(self, client: ClientInfo):
        if not client.username:
            return IGNORE, {}
        try:
            row = await self.connector.find_one(
                self.collection, {self.filter_field: client.username}
            )
        except Exception:
            log.exception("mongo authn failed")
            return IGNORE, {}
        if not row or not row.get("password_hash"):
            return IGNORE, {}
        ok = verify_password(
            client.password,
            str(row["password_hash"]),
            algorithm=self.algorithm,
            salt=str(row.get("salt") or ""),
            salt_position=self.salt_position,
            iterations=self.iterations,
        )
        if not ok:
            return DENY, {}
        return ALLOW, {
            "is_superuser": bool(row.get("is_superuser") or False)
        }

    async def close(self) -> None:
        await self.connector.close()


class MongoAuthorizer:
    """ACL rows from a collection, prefetched at CONNECT into the
    access layer's cache (emqx_authz_mongodb): documents carry
    ``permission``, ``action``, and ``topics`` (list) or ``topic``."""

    def __init__(
        self,
        connector: MongoConnector,
        collection: str = "mqtt_acl",
        filter_field: str = "username",
    ) -> None:
        self.connector = connector
        self.collection = collection
        self.filter_field = filter_field

    async def fetch_rows(self, client: ClientInfo) -> List[Dict]:
        docs = await self.connector.find(
            self.collection,
            {self.filter_field: client.username or ""},
        )
        rows: List[Dict] = []
        for d in docs:
            topics = d.get("topics") or (
                [d["topic"]] if d.get("topic") else []
            )
            for t in topics:
                rows.append({
                    "permission": d.get("permission", ALLOW),
                    "action": d.get("action", "all"),
                    "topic": t,
                })
        return rows

    async def close(self) -> None:
        await self.connector.close()
