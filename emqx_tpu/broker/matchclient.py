"""Worker-side client for the multicore match service.

`ServiceMatchEngine` is a drop-in `MatchEngine` for broker workers in
a `multicore` pool: every mutation updates a local HOST-ONLY mirror
(the superclass, pinned ``use_device=False``) AND streams a route
delta to the match service, and every publish window is submitted
over the worker's shared-memory `WindowRing` with a doorbell on the
control socket.  The mirror is the correctness anchor: any ring
trouble (service down, ring full, timeout, injected fault) degrades
THAT WINDOW to the in-process host path, which is bit-identical to
what the service computes — the referee property the multicore tests
pin.

Ordering makes the service exact, not approximate: route deltas and
window doorbells share one ordered control stream, so a window
submitted after `insert` returned is always matched against a route
table that includes that insert.  On re-attach (service restart) the
client replays its full route set from the mirror BEFORE new windows
flow, under the same write lock, so the stream stays ordered.

Slot lifetime under faults: a window that times out ABANDONS its slot
(quarantined in ``_abandoned``) instead of freeing it — a hung
service incarnation may still write there, and freeing would let a
fresh request be overwritten.  Abandoned slots return to the free
list when their late completion arrives or when the incarnation
provably dies (EOF → detach).

Threading: mutations arrive on the event loop, window submit/finish
on batcher executor threads, decide on the loop, and completions on
the dedicated reader thread.  ALL client state is guarded by
``_lk``/``_cond``; control-socket writes serialize under ``_slk``.
Lock order is ``_slk`` outer, ``_lk`` inner — never the reverse.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from typing import Dict, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import failpoints, flightrec
from ..engine import MatchEngine
from ..ops import matchsvc as wire
from . import shmring

log = logging.getLogger("emqx_tpu.matchclient")

_ROUTE_CHUNK = 2000  # route-replay entries per control line


class ServiceMatchEngine(MatchEngine):
    """MatchEngine facade that matches/decides via the shared service
    (shm ring + unix control socket) and falls back per-window to its
    own bit-identical host mirror."""

    def __init__(
        self,
        socket_path: str,
        worker_id: int,
        ring_slots: int = 8,
        ring_slot_bytes: int = 1 << 18,
        decide_min: int = 64,
        rpc_timeout: float = 2.0,
        reconnect_backoff: float = 0.2,
        **engine_kw,
    ) -> None:
        # the mirror must never grab the device the service owns
        engine_kw["use_device"] = False
        super().__init__(**engine_kw)
        self.socket_path = socket_path
        self.worker_id = int(worker_id)
        self.decide_min = int(decide_min)
        self.rpc_timeout = float(rpc_timeout)
        self.reconnect_backoff = float(reconnect_backoff)
        self._ring = shmring.WindowRing.create(
            slots=ring_slots, slot_bytes=ring_slot_bytes
        )
        self._lk = threading.Lock()
        self._cond = threading.Condition(self._lk)
        self._slk = threading.Lock()  # control-socket write serial
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._attached = False
        self._svc_device = False
        self._closed = False
        self._epoch = 0
        self._seq = 0
        self._rseq = 0
        self._done: Dict[int, Dict] = {}       # seq -> doorbell obj
        self._waiting: Set[int] = set()
        self._abandoned: Dict[int, int] = {}   # seq -> quarantined slot
        self._fid_id: Dict[Hashable, int] = {}
        self._fid_obj: Dict[int, Hashable] = {}
        self._next_fid = 0
        self._cols_sent_rev: Optional[int] = None
        self.svc_stats = {
            "windows": 0, "decides": 0, "fallbacks": 0, "ring_full": 0,
            "reconnects": 0, "route_lines": 0, "quarantined": 0,
            "oversize": 0,
        }
        # observability wiring (set by the owning Broker): the flight
        # recorder sees ring-full edges / detaches and carries the
        # cross-process dump broadcast; the metrics registry gets the
        # multicore.ring.* counters
        self.flight = None
        self.metrics = None
        self._flight_pending: Optional[Tuple[str, str]] = None
        self._svc_remote: Dict = {}   # last pong payload from service
        self._ring_full_log_ts = 0.0  # rate-limits the degrade warning
        self._reader = threading.Thread(
            target=self._reader_main,
            name=f"matchsvc-client-w{worker_id}", daemon=True,
        )
        self._reader.start()

    # ------------------------------------------------------ lifecycle

    @property
    def ring_name(self) -> str:
        return self._ring.name

    @property
    def attached(self) -> bool:
        with self._lk:
            return self._attached

    def service_info(self) -> Dict:
        """Attachment + fallback counters for /api/v5/nodes, plus the
        ring occupancy snapshot and the service's last pong payload
        (service-side counters + stage histograms)."""
        with self._lk:
            return {
                "attached": self._attached,
                "service_device": self._svc_device,
                "epoch": self._epoch,
                "ring_free": self._ring.free_slots(),
                "ring": self._ring.stats(),
                "service": dict(self._svc_remote),
                **dict(self.svc_stats),
            }

    def poll_service(self) -> bool:
        """Fire-and-forget service stats poll (1 Hz from the broker
        tick): the pong lands on the reader thread and is cached in
        ``_svc_remote`` for service_info / /metrics."""
        return self._send({"t": "ping"})

    def flight_broadcast(self, trig_id: str, reason: str) -> None:
        """Carry a flight-dump trigger to the service (which dumps its
        own ring under the same id and relays to the other workers).
        When the anomaly IS the lost service connection, the line is
        queued and sent right after the next successful re-attach —
        the service's post-restart incarnation still holds its
        (fresh) ring, and every sibling worker still holds the window
        of history that matters."""
        msg = {"t": "flight", "id": trig_id, "reason": reason,
               "worker": self.worker_id}
        if not self._send(msg):
            with self._lk:
                if not self._closed:
                    self._flight_pending = (trig_id, reason)

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._attached = False
            sock = self._sock
            self._sock = None
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
        self._reader.join(timeout=2.0)
        self._ring.close()

    # ---------------------------------------------------- route sync

    def _fid_for(self, fid: Hashable) -> int:
        """Interned wire id for a fid object.  Caller holds ``_lk``."""
        fid_id = self._fid_id.get(fid)
        if fid_id is None:
            fid_id = self._next_fid
            self._next_fid += 1
            self._fid_id[fid] = fid_id
            self._fid_obj[fid_id] = fid
        return fid_id

    def insert(self, flt: str, fid: Hashable) -> None:
        super().insert(flt, fid)
        self._route_send([(flt, fid)], ())

    def insert_many(self, pairs: Sequence[Tuple[str, Hashable]]) -> None:
        super().insert_many(pairs)
        self._route_send(pairs, ())

    def delete(self, fid: Hashable) -> bool:
        ok = super().delete(fid)
        if ok:
            self._route_send((), (fid,))
        return ok

    def _route_send(self, add, delete) -> None:
        """Stream one route delta; a detached service just skips (the
        re-attach replay covers it from the mirror)."""
        with self._slk:
            with self._lk:
                if not self._attached or self._closed:
                    return
                msg = {"t": "routes", "seq": self._rseq}
                self._rseq += 1
                if add:
                    msg["add"] = [
                        [self._fid_for(fid), flt] for flt, fid in add
                    ]
                if delete:
                    dels = []
                    for fid in delete:
                        fid_id = self._fid_id.pop(fid, None)
                        if fid_id is not None:
                            self._fid_obj.pop(fid_id, None)
                            dels.append(fid_id)
                    if not dels and not add:
                        return
                    msg["del"] = dels
                sock = self._sock
            self._send_locked(sock, msg)

    def _route_snapshot(self) -> List[List]:
        """Full (fid_id, filter) replay list from the mirror.  Caller
        holds ``_lk``; mirror reads take the engine's own ``_mlock``
        (strictly after ``_lk`` in every code path, never inverted)."""
        with self._mlock:
            pairs = list(self._by_fid.items())
        return [[self._fid_for(fid), flt] for fid, flt in pairs]

    # ------------------------------------------------------ transport

    def _send_locked(self, sock: Optional[socket.socket],
                     obj: Dict) -> bool:
        """Write one control line.  Caller holds ``_slk``."""
        if sock is None:
            return False
        try:
            sock.sendall(json.dumps(obj).encode() + b"\n")
            return True
        except OSError:
            return False

    def _send(self, obj: Dict) -> bool:
        with self._slk:
            with self._lk:
                if not self._attached:
                    return False
                sock = self._sock
            return self._send_locked(sock, obj)

    # --------------------------------------------------- reader thread

    def _reader_main(self) -> None:
        backoff = self.reconnect_backoff
        while True:
            with self._lk:
                if self._closed:
                    return
            sock = self._reconnect_once()
            if sock is None:
                time.sleep(min(backoff, 2.0))
                backoff = min(backoff * 2, 2.0)
                continue
            backoff = self.reconnect_backoff
            try:
                self._serve_conn(sock)
            finally:
                self._detach(sock)

    def _reconnect_once(self) -> Optional[socket.socket]:
        """One attach attempt: connect, hello, replay the full route
        set, and only then mark attached (ordered with ``_slk`` held so
        no delta can slip ahead of the replay)."""
        sock = None
        try:
            if failpoints.evaluate(
                "multicore.service.restart", key=str(self.worker_id)
            ) == "drop":
                raise ConnectionError("attach attempt dropped")
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.rpc_timeout)
            sock.connect(self.socket_path)
            rfile = sock.makefile("rb")
            with self._lk:
                if self._closed:
                    raise ConnectionError("client closed")
                epoch = self._epoch + 1
            sock.sendall(json.dumps({
                "t": "hello", "worker": self.worker_id, "epoch": epoch,
                "ring": self._ring.name,
            }).encode() + b"\n")
            reply = json.loads(rfile.readline() or b"{}")
            if reply.get("t") != "hello_ok":
                raise ConnectionError(f"hello rejected: {reply}")
            with self._slk:
                with self._cond:
                    if self._closed:
                        raise ConnectionError("client closed")
                    snapshot = self._route_snapshot()
                    self._epoch = epoch
                    self._sock = sock
                    self._rfile = rfile
                    self._svc_device = bool(reply.get("device"))
                    self._attached = True
                    self._cols_sent_rev = None
                    # the previous incarnation is gone: quarantined
                    # slots can never be written again
                    for slot in self._abandoned.values():
                        self._ring.release(slot)
                    self._abandoned.clear()
                    self.svc_stats["reconnects"] += 1
                    self._cond.notify_all()
                for i in range(0, len(snapshot), _ROUTE_CHUNK):
                    self._send_locked(sock, {
                        "t": "routes", "seq": 0,
                        "add": snapshot[i:i + _ROUTE_CHUNK],
                    })
                    with self._lk:
                        self.svc_stats["route_lines"] += 1
                # a dump broadcast that raced the outage goes out the
                # moment the control stream exists again, so the
                # restarted service still joins the correlated capture
                with self._lk:
                    pending = self._flight_pending
                    self._flight_pending = None
                if pending is not None:
                    self._send_locked(sock, {
                        "t": "flight", "id": pending[0],
                        "reason": pending[1], "worker": self.worker_id,
                    })
            sock.settimeout(None)
            log.info("attached to match service %s (epoch %d, "
                     "device=%s, %d routes)", self.socket_path, epoch,
                     reply.get("device"), len(snapshot))
            return sock
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            log.debug("match service attach failed: %s", exc)
            if sock is not None:
                sock.close()
            return None

    def _serve_conn(self, sock: socket.socket) -> None:
        rfile = self._rfile
        while True:
            try:
                line = rfile.readline()
            except OSError:
                return
            if not line:
                return
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                log.warning("bad service line: %r", line[:80])
                continue
            t = obj.get("t")
            if t in ("c", "e"):
                seq = int(obj.get("seq", -1))
                with self._cond:
                    slot = self._abandoned.pop(seq, None)
                    if slot is not None:
                        # late completion for a timed-out window: the
                        # service is done writing, the slot is safe
                        self._ring.release(slot)
                    elif seq in self._waiting:
                        self._done[seq] = obj
                        self._cond.notify_all()
            elif t == "flight":
                # correlated dump request initiated elsewhere in the
                # pool: freeze + persist THIS worker's ring under the
                # initiator's id (idempotent per id)
                fl = self.flight
                if fl is not None:
                    fl.dump_remote(
                        str(obj.get("id") or ""),
                        str(obj.get("reason") or ""),
                    )
            elif t == "pong":
                with self._lk:
                    self._svc_remote = {
                        "stats": obj.get("stats") or {},
                        "hist": obj.get("hist") or {},
                        "routes": obj.get("routes"),
                        "flight": obj.get("flight") or {},
                        "at": time.time(),
                    }
            # routes_ok / unknown lines are informational

    def _detach(self, sock: socket.socket) -> None:
        with self._cond:
            was_attached = self._attached
            closed = self._closed
            dead_epoch = self._epoch
            self._attached = False
            self._svc_device = False
            if self._sock is sock:
                self._sock = None
            # EOF proves the incarnation is dead: nothing will write
            # these slots again
            for slot in self._abandoned.values():
                self._ring.release(slot)
            self._abandoned.clear()
            self._done.clear()
            self._cond.notify_all()
        sock.close()
        # outside the locks: the trigger dumps and then broadcasts via
        # flight_broadcast, which re-enters _slk/_lk
        if was_attached and not closed:
            fl = self.flight
            if fl is not None:
                # epoch-keyed deterministic id: every worker watching
                # incarnation N die mints the SAME id, so one service
                # death yields one correlated capture even though the
                # relay hub is down at detection time
                fl.service_restart({
                    "socket": self.socket_path,
                    "worker": self.worker_id,
                }, key=f"e{dead_epoch}")

    # ------------------------------------------------------- windows

    def _note_ring_full(self) -> None:
        """Ring-full degrade bookkeeping: counters, a flight event,
        and a rate-limited warning that names WHICH ring saturated and
        at what depth (the window itself degrades to the in-process
        path — correct, just slower)."""
        with self._lk:
            self.svc_stats["ring_full"] += 1
        m = self.metrics
        if m is not None:
            m.inc("multicore.ring.full")
        st = self._ring.stats()
        fl = self.flight
        if fl is not None:
            fl.record(flightrec.EV_RING_FULL, float(st["slots"]),
                      float(st["full"]))
        now = time.monotonic()
        if now - self._ring_full_log_ts >= 1.0:
            self._ring_full_log_ts = now
            log.warning(
                "worker %d ring %s full at depth %d/%d (hwm %d, "
                "%d refusals total); window degrades to in-process "
                "match", self.worker_id, st["name"], st["in_flight"],
                st["slots"], st["high_watermark"], st["full"],
            )

    def _note_oversize(self) -> None:
        with self._lk:
            self.svc_stats["oversize"] += 1
        m = self.metrics
        if m is not None:
            m.inc("multicore.ring.oversize")

    def _ring_submit(self, topics: Sequence[str], congested: bool):
        """Submit one match window over the ring.  Returns a pending
        handle, or None → the caller serves the window in-process."""
        if failpoints.enabled:
            if failpoints.evaluate(
                "multicore.ring.submit", key=str(self.worker_id)
            ) == "drop":
                return None
        with self._lk:
            if not self._attached or self._closed:
                return None
            epoch = self._epoch
        try:
            slot = self._ring.acquire()
        except shmring.RingFull:
            self._note_ring_full()
            return None
        with self._lk:
            self._seq += 1
            seq = self._seq
        try:
            self._ring.write(
                slot, epoch, seq, shmring.KIND_MATCH_REQ,
                wire.pack_match_req(list(topics), congested),
            )
        except ValueError:  # window exceeds slot payload
            self._ring.release(slot)
            self._note_oversize()
            return None
        with self._lk:
            self._waiting.add(seq)
        if not self._send({"t": "w", "slot": slot, "seq": seq}):
            with self._lk:
                self._waiting.discard(seq)
            self._ring.release(slot)
            return None
        return (epoch, seq, slot)

    def _ring_complete(self, epoch: int, seq: int, slot: int
                       ) -> Optional[bytes]:
        """Wait out one submitted window; returns the raw response
        payload or None → fallback.  Never leaks the slot: success and
        hard errors free it, a timeout quarantines it (the service may
        still write there), and detach/attach drains the quarantine."""
        try:
            if failpoints.enabled:
                if failpoints.evaluate(
                    "multicore.ring.complete", key=str(seq)
                ) == "drop":
                    raise ConnectionError("completion dropped")
            deadline = time.monotonic() + self.rpc_timeout
            with self._cond:
                while True:
                    obj = self._done.pop(seq, None)
                    if obj is not None:
                        self._waiting.discard(seq)
                        break
                    if (self._closed or not self._attached
                            or self._epoch != epoch):
                        # incarnation gone: slot provably unreachable
                        self._waiting.discard(seq)
                        self._ring.release(slot)
                        return None
                    left = deadline - time.monotonic()
                    if left <= 0:
                        self._waiting.discard(seq)
                        self._abandoned[seq] = slot
                        self.svc_stats["quarantined"] += 1
                        m = self.metrics
                        if m is not None:
                            m.inc("multicore.ring.quarantined")
                        return None
                    self._cond.wait(left)
            if obj.get("t") != "c":
                self._ring.release(slot)
                return None
            got = self._ring.read(slot, epoch, seq)
            self._ring.release(slot)
            if got is None:
                return None
            return got[1]
        except failpoints.FailpointPanic:
            raise
        except Exception:
            with self._cond:
                self._waiting.discard(seq)
                self._abandoned[seq] = slot
                self.svc_stats["quarantined"] += 1
            m = self.metrics
            if m is not None:
                m.inc("multicore.ring.quarantined")
            return None

    # --------------------------------------------- MatchEngine facade

    def match_batch_submit(
        self, topics: Sequence[str], congested: bool = False,
        _force_device: bool = False,
    ):
        handle = self._ring_submit(topics, congested)
        if handle is not None:
            return ("svc", handle, list(topics))
        return super().match_batch_submit(
            topics, congested, _force_device=_force_device
        )

    def match_batch_finish(self, pending, info=None):
        if pending[0] != "svc":
            return super().match_batch_finish(pending, info=info)
        _, (epoch, seq, slot), topics = pending
        payload = self._ring_complete(epoch, seq, slot)
        if payload is None:
            with self._lk:
                self.svc_stats["fallbacks"] += 1
            if info is not None:
                info["path"] = "host-fallback"
            return self.match_batch_host(topics)
        try:
            id_rows = wire.unpack_match_resp(payload)
        except Exception:
            log.exception("bad match response for window of %d",
                          len(topics))
            if info is not None:
                info["path"] = "host-fallback"
            return self.match_batch_host(topics)
        with self._lk:
            fo = self._fid_obj
            # an id deleted between service match and here maps to
            # nothing — same outcome as a local match after the delete
            out = [
                {fo[i] for i in (int(x) for x in row) if i in fo}
                for row in id_rows
            ]
            self.svc_stats["windows"] += 1
        if info is not None:
            info["path"] = "svc"
        return out

    def match_batch(self, topics: Sequence[str],
                    congested: bool = False):
        """Loop-thread sync matches (forwarded dispatch, mgmt probes)
        stay on the local mirror: never block the event loop on the
        ring round-trip."""
        return super().match_batch_finish(
            super().match_batch_submit(topics, congested)
        )

    def decide_window(
        self,
        cols: Tuple,
        rev: int,
        opts_rows: np.ndarray,
        client_rows: np.ndarray,
        msg_idx: np.ndarray,
        m_qos: np.ndarray,
        m_retain: np.ndarray,
        m_from_row: np.ndarray,
    ) -> Tuple[np.ndarray, str]:
        with self._lk:
            use_svc = (
                self._attached and self._svc_device
                and len(opts_rows) >= self.decide_min
            )
        if use_svc:
            out = self._ring_decide(
                cols, rev, opts_rows, client_rows, msg_idx, m_qos,
                m_retain, m_from_row,
            )
            if out is not None:
                return out
            with self._lk:
                self.svc_stats["fallbacks"] += 1
        return super().decide_window(
            cols, rev, opts_rows, client_rows, msg_idx, m_qos,
            m_retain, m_from_row,
        )

    def _ring_decide(self, cols, rev, opts_rows, client_rows, msg_idx,
                     m_qos, m_retain, m_from_row):
        """Ship one decide window to the service's device kernel; the
        SubOpts columns ride along only when their rev changed since
        the last ship (the service caches them per worker)."""
        if failpoints.enabled:
            if failpoints.evaluate(
                "multicore.ring.submit", key="decide"
            ) == "drop":
                return None
        with self._lk:
            if not self._attached or self._closed:
                return None
            epoch = self._epoch
            send_cols = self._cols_sent_rev != rev
        try:
            slot = self._ring.acquire()
        except shmring.RingFull:
            self._note_ring_full()
            return None
        with self._lk:
            self._seq += 1
            seq = self._seq
        try:
            self._ring.write(
                slot, epoch, seq, shmring.KIND_DECIDE_REQ,
                wire.pack_decide_req(
                    cols if send_cols else None, rev, opts_rows,
                    client_rows, msg_idx, m_qos, m_retain, m_from_row,
                ),
            )
        except ValueError:
            self._ring.release(slot)
            self._note_oversize()
            return None
        with self._lk:
            self._waiting.add(seq)
        if not self._send({"t": "w", "slot": slot, "seq": seq}):
            with self._lk:
                self._waiting.discard(seq)
            self._ring.release(slot)
            return None
        if send_cols:
            with self._lk:
                # ordered stream: the service caches these cols before
                # any later window at this rev is served
                if self._epoch == epoch:
                    self._cols_sent_rev = rev
        payload = self._ring_complete(epoch, seq, slot)
        if payload is None:
            with self._lk:
                if self._cols_sent_rev == rev:
                    self._cols_sent_rev = None  # resend next time
            return None
        try:
            packed, path = wire.unpack_decide_resp(payload)
        except Exception:
            log.exception("bad decide response")
            return None
        if len(packed) != len(opts_rows):
            return None
        with self._lk:
            self.svc_stats["decides"] += 1
        return packed, path


__all__ = ["ServiceMatchEngine"]
