"""Multi-core broker: worker processes sharing one listening port.

The reference runs on every BEAM scheduler via its broker/router
pools (/root/reference/apps/emqx/src/emqx_broker.erl:539-540, esockd
acceptor pools); a single asyncio loop caps this broker at one core.
The multi-core launcher spawns N WORKER PROCESSES that each run the
full broker:

  * every worker binds the SAME MQTT port with SO_REUSEPORT — the
    kernel spreads accepted connections across workers (the acceptor
    pool);
  * workers cluster over loopback using the ordinary inter-node
    transport (route-delta replication + binary-wire forwards), so a
    publish accepted by worker A reaches subscribers owned by worker
    B exactly as it would cross real nodes — no new protocol, and a
    multi-host deployment composes by seeding workers at other hosts.

Usage: ``python -m emqx_tpu.broker --workers N [--port P]`` or
`spawn_workers()` programmatically (the bench drives it that way).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.multicore")


def free_ports(n: int) -> List[int]:
    """Probe N currently-free loopback ports (shared by the launcher,
    its bench tool, and tests — TOCTOU applies, as with any probe)."""
    return _free_ports(n)


def _free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def worker_configs(
    n_workers: int,
    port: int,
    bind: str = "0.0.0.0",
    base_config: Optional[Dict] = None,
    use_device: Optional[bool] = False,
    tracing: Optional[Dict] = None,
) -> List[Dict]:
    """Per-worker config dicts: shared REUSEPORT listener + loopback
    cluster full-mesh seeds.  ``use_device=False`` by default — worker
    processes must not fight over one TPU; run a single-process broker
    for the device match path, or give exactly one worker the device.

    ``tracing`` (a TracingConfig-shaped dict) arms the lifecycle
    tracer in EVERY worker: cross-worker submissions ride the ordinary
    inter-node forward, so a sampled publish accepted by worker A and
    delivered by worker B yields one connected trace with per-worker
    process tracks (node_name = ``worker<i>``) in the merged Perfetto
    timeline.  When the base config enables the management API, each
    worker gets its OWN api port (they cannot share one), so every
    worker's trace store is REST-queryable for the merge.
    """
    base_api = dict((base_config or {}).get("api") or {})
    # ONE probe for every port this pool needs: drawing api ports from
    # a second call could hand back a just-released cluster port (the
    # probe sockets close between calls) and a worker would fail to
    # bind; a single call holds all sockets open simultaneously, so
    # the ports are guaranteed distinct
    want_api = bool(base_api.get("enable"))
    ports = _free_ports(n_workers * 2 if want_api else n_workers)
    cluster_ports = ports[:n_workers]
    api_ports = ports[n_workers:] if want_api else None
    configs = []
    for i in range(n_workers):
        cfg = dict(base_config or {})
        cfg["node_name"] = f"worker{i}"
        cfg["listeners"] = [{
            "name": "tcp_shared",
            "bind": bind,
            "port": port,
            "reuse_port": True,
        }]
        engine = dict(cfg.get("engine") or {})
        if use_device is not None:
            engine["use_device"] = use_device
        cfg["engine"] = engine
        if tracing is not None:
            cfg["tracing"] = dict(tracing)
        if api_ports is not None:
            cfg["api"] = {**base_api, "port": api_ports[i]}
        cfg["cluster"] = {
            "enable": True,
            "bind": "127.0.0.1",
            "port": cluster_ports[i],
            "heartbeat_interval": 0.5,
            "down_after": 3.0,
            "seeds": [
                [f"worker{j}", "127.0.0.1", cluster_ports[j]]
                for j in range(n_workers) if j != i
            ],
        }
        configs.append(cfg)
    return configs


class WorkerPool:
    """Spawn + supervise the worker processes."""

    def __init__(self, configs: List[Dict],
                 log_dir: Optional[str] = None) -> None:
        self.configs = configs
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="emqx-mc-")
        self.procs: List[subprocess.Popen] = []
        self._conf_paths: List[str] = []

    def _spawn_one(self, i: int, mode: str = "w") -> subprocess.Popen:
        cfg = self.configs[i]
        env = dict(os.environ)
        if not (cfg.get("engine") or {}).get("use_device"):
            # host-engine workers must not initialize (or fight over)
            # the TPU backend a sitecustomize may pre-wire — the
            # RESTART path must apply the same override as the first
            # spawn
            env["JAX_PLATFORMS"] = "cpu"
        log_f = open(
            os.path.join(self.log_dir, f"worker{i}.log"), mode
        )
        return subprocess.Popen(
            [sys.executable, "-m", "emqx_tpu.broker",
             "--config", self._conf_paths[i]],
            stdout=log_f, stderr=subprocess.STDOUT, env=env,
        )

    def start(self) -> None:
        os.makedirs(self.log_dir, exist_ok=True)
        for i, cfg in enumerate(self.configs):
            conf_path = os.path.join(self.log_dir, f"worker{i}.json")
            with open(conf_path, "w") as f:
                json.dump(cfg, f, indent=1)
            self._conf_paths.append(conf_path)
        self.procs = [
            self._spawn_one(i) for i in range(len(self.configs))
        ]
        log.info("spawned %d workers (logs in %s)",
                 len(self.procs), self.log_dir)

    def wait_ready(self, port: int, timeout: float = 60.0) -> None:
        """Block until the shared port accepts (all workers share it,
        so the first acceptor proves the pool is serving)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(p.poll() is not None for p in self.procs):
                dead = [
                    i for i, p in enumerate(self.procs)
                    if p.poll() is not None
                ]
                raise RuntimeError(
                    f"workers {dead} exited during startup; see "
                    f"{self.log_dir}"
                )
            try:
                with socket.create_connection(
                    ("127.0.0.1", port), timeout=0.5
                ):
                    return
            except OSError:
                time.sleep(0.2)
        raise TimeoutError(f"port {port} not accepting after {timeout}s")

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.poll() is None)

    def stop(self, timeout: float = 10.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        deadline = time.monotonic() + timeout
        for p in self.procs:
            try:
                p.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs = []


def spawn_workers(
    n_workers: int,
    port: int,
    bind: str = "0.0.0.0",
    base_config: Optional[Dict] = None,
    use_device: Optional[bool] = False,
    tracing: Optional[Dict] = None,
) -> WorkerPool:
    pool = WorkerPool(worker_configs(
        n_workers, port, bind=bind, base_config=base_config,
        use_device=use_device, tracing=tracing,
    ))
    pool.start()
    return pool


def main(n_workers: int, port: int, bind: str = "0.0.0.0",
         base_config: Optional[Dict] = None) -> None:
    """Foreground supervisor: run the pool, restart dead workers,
    terminate cleanly on SIGINT/SIGTERM."""
    pool = spawn_workers(n_workers, port, bind=bind,
                         base_config=base_config)
    stopping = False

    def _stop(_sig, _frm):
        nonlocal stopping
        stopping = True

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    try:
        # inside try/finally: a startup failure must stop the
        # SURVIVING workers too, or zombies keep sharing the port
        pool.wait_ready(port)
        print(f"emqx_tpu multicore: {n_workers} workers on :{port} "
              f"(logs: {pool.log_dir})", flush=True)
        while not stopping:
            time.sleep(1.0)
            for i, p in enumerate(pool.procs):
                if p.poll() is not None and not stopping:
                    log.warning("worker %d died (rc=%s); restarting",
                                i, p.returncode)
                    pool.procs[i] = pool._spawn_one(i, mode="a")
    finally:
        pool.stop()
