"""Multi-core broker: N worker processes × one shared match service.

The reference runs one ``emqx_broker`` per BEAM scheduler over ONE
shared ``emqx_router`` table (/root/reference/apps/emqx/src/
emqx_broker.erl:539-540, esockd acceptor pools); a single asyncio
loop caps this broker at one core.  The multi-core launcher maps that
layer split onto processes:

  * **Layer 1 — workers**: every worker binds the SAME MQTT port with
    SO_REUSEPORT (the kernel spreads accepted connections — the
    acceptor pool) and owns its connections' sessions, channels,
    inflight windows, olp load ladder, and SyncGate durability
    barrier.  Workers still cluster over loopback with the ordinary
    inter-node transport (route-delta replication + binary-wire
    forwards), so a publish accepted by worker A reaches subscribers
    owned by worker B exactly as it would cross real nodes.
  * **Layer 2 — the match service** (`ops.matchsvc`): one process
    owns the trie-automaton, the router CSR with interned per-worker
    fids, and the device decide kernel.  Workers submit dispatch
    windows over per-worker shared-memory rings
    (`broker.shmring.WindowRing`) via `broker.matchclient.
    ServiceMatchEngine`; any service trouble degrades per-window to
    each worker's bit-identical in-process host mirror, and workers
    re-attach automatically when the service returns.

Resuming durable sessions shard across workers by client-id hash
(`broker.resume.shard_of`): each worker's durable data dir is its
shard (``<data_dir>/worker<i>``), so a mass reconnect spreads its
replay floor over the pool and no two workers ever hold rival
checkpoints for one client.

Usage: ``python -m emqx_tpu.broker --workers N [--port P]
[--no-match-service]`` or `spawn_workers()` programmatically (the
bench drives it that way).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from .resume import shard_of  # re-exported: the pool's shard rule

log = logging.getLogger("emqx_tpu.multicore")

__all__ = [
    "PortReservation", "WorkerPool", "free_ports", "main",
    "shard_of", "spawn_workers", "worker_configs",
]


class PortReservation:
    """Loopback ports held OPEN (bound sockets) until their owner
    spawns — the fix for the probe-then-close TOCTOU where two
    concurrent pools could draw the same "free" port between the
    probe socket closing and the worker binding.  `release(port)` is
    called immediately before the spawn that binds it, shrinking the
    race window from pool-setup-wide to one exec."""

    def __init__(self, n: int, host: str = "127.0.0.1") -> None:
        self._socks: Dict[int, socket.socket] = {}
        self.ports: List[int] = []
        for _ in range(n):
            s = socket.socket()
            # REUSEADDR so a just-closed reservation (TIME_WAIT-free
            # loopback bind) never blocks the worker's real bind
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            port = s.getsockname()[1]
            self.ports.append(port)
            self._socks[port] = s
        self.host = host

    def release(self, port: int) -> None:
        """Free one port for its owner to bind (idempotent)."""
        s = self._socks.pop(port, None)
        if s is not None:
            s.close()

    def release_all(self) -> None:
        for port in list(self._socks):
            self.release(port)

    def __enter__(self) -> "PortReservation":
        return self

    def __exit__(self, *exc) -> None:
        self.release_all()


def free_ports(n: int) -> List[int]:
    """Probe N currently-free loopback ports.  Kept for callers that
    only need numbers (their own TOCTOU to manage); pool spawning
    itself uses `PortReservation` so concurrent pools can't collide."""
    with PortReservation(n) as res:
        return list(res.ports)


def _free_ports(n: int) -> List[int]:
    return free_ports(n)


def worker_configs(
    n_workers: int,
    port: int,
    bind: str = "0.0.0.0",
    base_config: Optional[Dict] = None,
    use_device: Optional[bool] = False,
    tracing: Optional[Dict] = None,
    olp: Optional[Dict] = None,
    service_socket: Optional[str] = None,
    reservation: Optional[PortReservation] = None,
) -> List[Dict]:
    """Per-worker config dicts: shared REUSEPORT listener + loopback
    cluster full-mesh seeds (+ the match-service attachment when
    ``service_socket`` is given).

    ``use_device=False`` by default — worker processes must not fight
    over one TPU; in the service topology the MATCH SERVICE owns the
    device and workers keep host-only mirrors, which is exactly this
    default.

    ``olp`` (an OlpConfig-shaped dict) arms the SAME load ladder in
    every worker — each worker samples its own loop lag/backlog and
    degrades independently (per-worker ``olp_level`` surfaces in the
    merged ``GET /api/v5/nodes``).

    ``tracing`` (a TracingConfig-shaped dict) arms the lifecycle
    tracer in EVERY worker: cross-worker submissions ride the ordinary
    inter-node forward, so a sampled publish accepted by worker A and
    delivered by worker B yields one connected trace with per-worker
    process tracks (node_name = ``worker<i>``) in the merged Perfetto
    timeline.  When the base config enables the management API, each
    worker gets its OWN api port (they cannot share one), so every
    worker's trace store is REST-queryable for the merge.

    ``reservation`` (optional, created internally when omitted) holds
    every drawn port's socket open; `WorkerPool` releases worker i's
    ports immediately before spawning worker i.
    """
    base_api = dict((base_config or {}).get("api") or {})
    # ONE reservation for every port this pool needs: drawing api
    # ports from a second probe could hand back a just-released
    # cluster port and a worker would fail to bind; one reservation
    # holds all sockets open simultaneously AND keeps holding them
    # until each owner spawns (the TOCTOU fix)
    want_api = bool(base_api.get("enable"))
    own_res = reservation is None
    res = reservation or PortReservation(
        n_workers * 2 if want_api else n_workers
    )
    ports = res.ports
    cluster_ports = ports[:n_workers]
    api_ports = ports[n_workers:n_workers * 2] if want_api else None
    base_durable = dict((base_config or {}).get("durable") or {})
    configs = []
    for i in range(n_workers):
        cfg = dict(base_config or {})
        cfg["node_name"] = f"worker{i}"
        cfg["listeners"] = [{
            "name": "tcp_shared",
            "bind": bind,
            "port": port,
            "reuse_port": True,
        }]
        engine = dict(cfg.get("engine") or {})
        if use_device is not None:
            engine["use_device"] = use_device
        cfg["engine"] = engine
        if tracing is not None:
            cfg["tracing"] = dict(tracing)
        if olp is not None:
            cfg["olp"] = {**dict(cfg.get("olp") or {}), **dict(olp)}
        if api_ports is not None:
            cfg["api"] = {**base_api, "port": api_ports[i]}
        cfg["multicore"] = {
            "n_workers": n_workers,
            "worker_id": i,
            "service_socket": service_socket or "",
        }
        if base_durable.get("enable"):
            # durable home shards: worker i owns the checkpoints +
            # captures of client ids hashing to shard i — separate
            # dirs, ONE canonical checkpoint per client
            resume = dict(base_durable.get("resume") or {})
            resume["shard_index"] = i
            resume["shard_count"] = n_workers
            cfg["durable"] = {
                **base_durable,
                "data_dir": os.path.join(
                    base_durable.get("data_dir", "data/ds"),
                    f"worker{i}",
                ),
                "resume": resume,
            }
        cfg["cluster"] = {
            "enable": True,
            "bind": "127.0.0.1",
            "port": cluster_ports[i],
            "heartbeat_interval": 0.5,
            "down_after": 3.0,
            "seeds": [
                [f"worker{j}", "127.0.0.1", cluster_ports[j]]
                for j in range(n_workers) if j != i
            ],
        }
        configs.append(cfg)
    if own_res:
        # caller only wanted config dicts (the legacy probe shape);
        # spawning callers pass/keep the reservation to hold the fix
        res.release_all()
    return configs


class WorkerPool:
    """Spawn + supervise the worker processes and (optionally) the
    shared match service."""

    def __init__(self, configs: List[Dict],
                 log_dir: Optional[str] = None,
                 reservation: Optional[PortReservation] = None,
                 service_socket: Optional[str] = None,
                 service_engine: Optional[Dict] = None) -> None:
        self.configs = configs
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="emqx-mc-")
        self.reservation = reservation
        self.service_socket = service_socket
        self.service_engine = service_engine
        self.procs: List[subprocess.Popen] = []
        self.service_proc: Optional[subprocess.Popen] = None
        self._conf_paths: List[str] = []
        # one shared flight-dump directory for the whole pool: a
        # correlated trigger makes every process persist its ring HERE,
        # so one `GET /api/v5/flight/{id}` (any worker) merges them all
        for cfg in self.configs:
            fl = dict(cfg.get("flight") or {})
            fl.setdefault(
                "dump_dir", os.path.join(self.log_dir, "flight")
            )
            cfg["flight"] = fl

    # ------------------------------------------------------- workers

    def _release_ports(self, cfg: Dict) -> None:
        """Free this worker's reserved ports right before its spawn —
        the narrow end of the TOCTOU fix."""
        if self.reservation is None:
            return
        cluster_port = (cfg.get("cluster") or {}).get("port")
        if cluster_port:
            self.reservation.release(int(cluster_port))
        api = cfg.get("api") or {}
        if api.get("enable") and api.get("port"):
            self.reservation.release(int(api["port"]))

    def _spawn_one(self, i: int, mode: str = "w") -> subprocess.Popen:
        cfg = self.configs[i]
        self._release_ports(cfg)
        env = dict(os.environ)
        if not (cfg.get("engine") or {}).get("use_device"):
            # host-engine workers must not initialize (or fight over)
            # the TPU backend a sitecustomize may pre-wire — the
            # RESTART path must apply the same override as the first
            # spawn.  In the service topology the device belongs to
            # the match service alone.
            env["JAX_PLATFORMS"] = "cpu"
        log_f = open(
            os.path.join(self.log_dir, f"worker{i}.log"), mode
        )
        return subprocess.Popen(
            [sys.executable, "-m", "emqx_tpu.broker",
             "--config", self._conf_paths[i]],
            stdout=log_f, stderr=subprocess.STDOUT, env=env,
        )

    # ------------------------------------------------- match service

    # FlightRecorder constructor keys a FlightConfig-shaped dict may
    # carry across the --flight-json boundary
    _FLIGHT_KEYS = (
        "enable", "ring_size", "notes_cap", "dump_dir", "max_dumps",
        "min_dump_interval", "watchdog_stall_ms", "slo_p99_ms",
        "fsync_stall_ms", "gc_stall_ms", "trigger_olp_level",
        "trigger_on_breaker", "trigger_on_restart", "trigger_on_fault",
    )

    def _service_flight_kw(self) -> Optional[Dict]:
        """The service's flight recorder settings: the pool's shared
        dump_dir + whatever the worker configs carry (minus the
        profiler-stage SLOs, which are worker-side sensors)."""
        if not self.configs:
            return None
        fl = dict(self.configs[0].get("flight") or {})
        if not fl.get("enable", True):
            return None
        fl.pop("slo_p99_ms", None)
        return {k: v for k, v in fl.items() if k in self._FLIGHT_KEYS}

    def _spawn_service(self, mode: str = "w") -> subprocess.Popen:
        assert self.service_socket is not None
        # a stale socket file from a previous incarnation would make
        # the fresh service fail its bind
        try:
            os.unlink(self.service_socket)
        except FileNotFoundError:
            pass
        argv = [sys.executable, "-m", "emqx_tpu.ops.matchsvc",
                "--socket", self.service_socket]
        if self.service_engine:
            argv += ["--engine-json", json.dumps(self.service_engine)]
        fl = self._service_flight_kw()
        if fl is not None:
            argv += ["--flight-json", json.dumps(fl)]
        log_f = open(
            os.path.join(self.log_dir, "matchsvc.log"), mode
        )
        return subprocess.Popen(
            argv, stdout=log_f, stderr=subprocess.STDOUT,
        )

    def restart_service(self) -> None:
        """Kill + respawn the match service (chaos surface: workers
        must degrade to their in-process mirrors and re-attach)."""
        if self.service_proc is not None:
            if self.service_proc.poll() is None:
                self.service_proc.kill()
                self.service_proc.wait()
            self.service_proc = self._spawn_service(mode="a")

    def service_alive(self) -> bool:
        return (self.service_proc is not None
                and self.service_proc.poll() is None)

    def wait_service(self, timeout: float = 30.0) -> None:
        """Block until the service's control socket accepts."""
        assert self.service_socket is not None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (self.service_proc is not None
                    and self.service_proc.poll() is not None):
                raise RuntimeError(
                    f"match service exited rc="
                    f"{self.service_proc.returncode}; see "
                    f"{self.log_dir}/matchsvc.log"
                )
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.connect(self.service_socket)
                return
            except OSError:
                time.sleep(0.1)
            finally:
                s.close()
        raise TimeoutError(
            f"match service socket {self.service_socket} not "
            f"accepting after {timeout}s"
        )

    # ----------------------------------------------------- lifecycle

    def start(self) -> None:
        os.makedirs(self.log_dir, exist_ok=True)
        for i, cfg in enumerate(self.configs):
            conf_path = os.path.join(self.log_dir, f"worker{i}.json")
            with open(conf_path, "w") as f:
                json.dump(cfg, f, indent=1)
            self._conf_paths.append(conf_path)
        if self.service_socket is not None:
            # service first: workers attach during startup instead of
            # spending their first windows on the fallback path
            self.service_proc = self._spawn_service()
            try:
                self.wait_service()
            except Exception:
                self.stop()
                raise
        self.procs = [
            self._spawn_one(i) for i in range(len(self.configs))
        ]
        if self.reservation is not None:
            # every owner has spawned; nothing left to hold
            self.reservation.release_all()
        log.info("spawned %d workers%s (logs in %s)",
                 len(self.procs),
                 " + match service" if self.service_proc else "",
                 self.log_dir)

    def wait_ready(self, port: int, timeout: float = 60.0) -> None:
        """Block until the shared port accepts (all workers share it,
        so the first acceptor proves the pool is serving)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(p.poll() is not None for p in self.procs):
                dead = [
                    i for i, p in enumerate(self.procs)
                    if p.poll() is not None
                ]
                raise RuntimeError(
                    f"workers {dead} exited during startup; see "
                    f"{self.log_dir}"
                )
            try:
                with socket.create_connection(
                    ("127.0.0.1", port), timeout=0.5
                ):
                    return
            except OSError:
                time.sleep(0.2)
        raise TimeoutError(f"port {port} not accepting after {timeout}s")

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.poll() is None)

    def stop(self, timeout: float = 10.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        deadline = time.monotonic() + timeout
        for p in self.procs:
            try:
                p.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs = []
        # the service stops LAST: workers flush their final windows
        # (or fall back) before their layer-2 half goes away
        if self.service_proc is not None:
            if self.service_proc.poll() is None:
                self.service_proc.send_signal(signal.SIGTERM)
                try:
                    self.service_proc.wait(5.0)
                except subprocess.TimeoutExpired:
                    self.service_proc.kill()
            self.service_proc = None
        if self.service_socket is not None:
            try:
                os.unlink(self.service_socket)
            except FileNotFoundError:
                pass
        if self.reservation is not None:
            self.reservation.release_all()


def spawn_workers(
    n_workers: int,
    port: int,
    bind: str = "0.0.0.0",
    base_config: Optional[Dict] = None,
    use_device: Optional[bool] = False,
    tracing: Optional[Dict] = None,
    olp: Optional[Dict] = None,
    match_service: bool = True,
    service_engine: Optional[Dict] = None,
    log_dir: Optional[str] = None,
) -> WorkerPool:
    """Spawn the full multicore topology: the shared match service
    (unless ``match_service=False`` pins the legacy independent-worker
    shape) plus N workers attached to it."""
    log_dir = log_dir or tempfile.mkdtemp(prefix="emqx-mc-")
    service_socket = (
        os.path.join(log_dir, "matchsvc.sock") if match_service
        else None
    )
    base_api = dict((base_config or {}).get("api") or {})
    want_api = bool(base_api.get("enable"))
    reservation = PortReservation(
        n_workers * 2 if want_api else n_workers
    )
    pool = WorkerPool(
        worker_configs(
            n_workers, port, bind=bind, base_config=base_config,
            use_device=use_device, tracing=tracing, olp=olp,
            service_socket=service_socket, reservation=reservation,
        ),
        log_dir=log_dir,
        reservation=reservation,
        service_socket=service_socket,
        service_engine=service_engine,
    )
    pool.start()
    return pool


def main(n_workers: int, port: int, bind: str = "0.0.0.0",
         base_config: Optional[Dict] = None,
         match_service: bool = True) -> None:
    """Foreground supervisor: run the pool, restart dead workers AND a
    dead match service (workers re-attach on their own), terminate
    cleanly on SIGINT/SIGTERM."""
    pool = spawn_workers(n_workers, port, bind=bind,
                         base_config=base_config,
                         match_service=match_service)
    stopping = False

    def _stop(_sig, _frm):
        nonlocal stopping
        stopping = True

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    try:
        # inside try/finally: a startup failure must stop the
        # SURVIVING workers too, or zombies keep sharing the port
        pool.wait_ready(port)
        print(f"emqx_tpu multicore: {n_workers} workers on :{port}"
              + (" + match service" if match_service else "")
              + f" (logs: {pool.log_dir})", flush=True)
        while not stopping:
            time.sleep(1.0)
            for i, p in enumerate(pool.procs):
                if p.poll() is not None and not stopping:
                    log.warning("worker %d died (rc=%s); restarting",
                                i, p.returncode)
                    pool.procs[i] = pool._spawn_one(i, mode="a")
            if (pool.service_socket is not None
                    and not pool.service_alive() and not stopping):
                log.warning("match service died; restarting "
                            "(workers serve from mirrors meanwhile)")
                pool.restart_service()
    finally:
        pool.stop()
